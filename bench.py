"""Benchmark: VerifyCommit hot path — 10k-validator ed25519 commit.

BASELINE.md north star: device batch verification vs the host per-signature
path (OpenSSL via `cryptography`, the fastest CPU verifier available here;
the reference's Go crypto/batch cannot run in this image — no Go toolchain).

Prints ONE JSON line:
  {"metric": "verify_commit_10k", "value": <device sigs/s>,
   "unit": "sigs/s", "vs_baseline": <device/host speedup>, "backend": ...}

Crash-proofing (the TPU plugin can hang or fail at backend init — observed
>120s hangs on bare `import jax`): the parent process never imports jax.
It probes the backend in a subprocess with a hard timeout, runs the real
benchmark in a worker subprocess, and falls back to the CPU backend (and
finally to a degraded-but-valid JSON line) instead of crashing. Exit code
is always 0 and exactly one JSON line is printed to stdout.

Timing is end-to-end per batch (host prep: packing + transfer + the device
ladder) — what VerifyCommit actually pays per commit.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Escalating probe timeouts: the TPU plugin has been observed to hang on
# one attempt and come up fine on the next — fight for it over a
# multi-minute window before conceding (round-2 lesson: one 120s probe
# gave up and the round recorded a CPU number).
PROBE_TIMEOUTS = tuple(
    float(t)
    for t in os.environ.get("TM_TPU_BENCH_PROBE_TIMEOUTS", "90,180,300").split(",")
)
WORKER_TIMEOUT = float(os.environ.get("TM_TPU_BENCH_WORKER_TIMEOUT", "900"))
ACCEL_ATTEMPTS = int(os.environ.get("TM_TPU_BENCH_ACCEL_ATTEMPTS", "2"))


def _cache_env(env: dict, cpu: bool = False) -> dict:
    env = dict(env)
    from tendermint_tpu.libs import jaxcache

    jaxcache.set_env(env, os.path.dirname(os.path.abspath(__file__)))
    if cpu:
        # CPU paths must not touch the remote-TPU relay at all: the axon
        # sitecustomize registers (and may dial) the PJRT plugin at
        # interpreter start whenever PALLAS_AXON_POOL_IPS is set.
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def _probe_backend() -> str:
    """Ask a subprocess what jax.default_backend() is, with escalating hard
    timeouts — survives a hung/broken PJRT plugin. Returns the backend
    name, or None if every probe failed (hang/crash)."""
    code = "import jax; print(jax.default_backend())"
    for attempt, timeout_s in enumerate(PROBE_TIMEOUTS):
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=timeout_s,
                env=_cache_env(os.environ), cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            if out.returncode == 0 and out.stdout.strip():
                return out.stdout.strip().splitlines()[-1]
            print(
                f"# backend probe attempt {attempt} rc={out.returncode}: "
                f"{out.stderr.strip()[-300:]}", file=sys.stderr,
            )
        except subprocess.TimeoutExpired:
            print(
                f"# backend probe attempt {attempt} timed out after "
                f"{timeout_s}s", file=sys.stderr,
            )
        time.sleep(5 * (attempt + 1))
    return None


def _run_worker(force_cpu: bool) -> dict | None:
    env = _cache_env(os.environ, cpu=force_cpu)
    env["TM_TPU_BENCH_WORKER"] = "1"
    stdout, stderr, rc = "", "", 0
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True, text=True, timeout=WORKER_TIMEOUT, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        stdout, stderr, rc = out.stdout, out.stderr, out.returncode
    except subprocess.TimeoutExpired as e:
        # salvage: the worker prints a partial JSON line right after the
        # primary measurement, so a stall in a SECONDARY benchmark must
        # not discard the headline number
        print(f"# bench worker timed out after {WORKER_TIMEOUT}s "
              f"(force_cpu={force_cpu}); salvaging partial output",
              file=sys.stderr)
        stdout = (e.stdout or b"")
        stderr = (e.stderr or b"")
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", "replace")
        if isinstance(stderr, bytes):
            stderr = stderr.decode("utf-8", "replace")
    sys.stderr.write(stderr[-4000:])
    if rc != 0:
        print(f"# bench worker rc={rc} (force_cpu={force_cpu})",
              file=sys.stderr)
        return None
    for line in reversed(stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    print("# bench worker emitted no JSON line", file=sys.stderr)
    return None


def main() -> None:
    backend = _probe_backend()
    print(f"# probed backend: {backend}", file=sys.stderr)
    # Fight for the accelerator: even when the probe failed (None), the
    # worker gets its own attempts under WORKER_TIMEOUT — a hung probe does
    # not mean the next plugin init will hang too. Only surrender to CPU
    # after every accel attempt has failed.
    result = None
    if backend != "cpu":
        for attempt in range(ACCEL_ATTEMPTS):
            result = _run_worker(force_cpu=False)
            if result is not None:
                break
            print(f"# accel worker attempt {attempt} failed", file=sys.stderr)
            time.sleep(10)
    if result is None:
        result = _run_worker(force_cpu=True)
    if result is None:
        result = {
            "metric": "verify_commit_10k", "value": 0.0, "unit": "sigs/s",
            "vs_baseline": 0.0, "backend": "none",
            "error": "benchmark worker failed on all backends",
        }
    print(json.dumps(result))


# ---------------------------------------------------------------------------
# Worker: the actual measurement (runs in a subprocess).
# ---------------------------------------------------------------------------


def _mp_verify_chunk(chunk) -> bool:
    from tendermint_tpu.crypto import ed25519 as _e

    return all(_e.verify_zip215_fast(p, m, s) for p, m, s in chunk)


def _host_multicore_rate(entries) -> float:
    """Strongest-CPU figure the 20x claim gets judged against: per-sig
    OpenSSL verify fanned over every core (the reference's Go batch
    verifier is single-threaded, but a fair host baseline isn't)."""
    import multiprocessing as mp

    nproc = min(mp.cpu_count(), 32)
    chunks = [entries[i::nproc] for i in range(nproc)]
    ctx = mp.get_context("spawn")  # no fork: jax/TPU client is live here
    with ctx.Pool(nproc) as pool:
        pool.map(_mp_verify_chunk, [c[:2] for c in chunks])  # warm imports
        t0 = time.perf_counter()
        oks = pool.map(_mp_verify_chunk, chunks)
    dt = time.perf_counter() - t0
    assert all(oks)
    return len(entries) / dt


def worker() -> None:
    import jax

    backend_kind = jax.default_backend()
    on_accel = backend_kind not in ("cpu",)
    n_sigs = int(os.environ.get("TM_TPU_BENCH_SIGS", "10000" if on_accel else "512"))
    # the timed loop below feeds one bucket directly (no chunking)
    n_sigs = min(n_sigs, 10240)

    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.ops import backend

    # Build a synthetic 10k-validator commit: unique keys, ~120B canonical
    # vote-sized messages (types/vote.go:93 sign bytes scale).
    entries = []
    msg_pad = b"\x08\x02\x10\x01" + b"p" * 100
    for i in range(n_sigs):
        sk = ed25519.gen_priv_key(i.to_bytes(32, "little"))
        msg = i.to_bytes(8, "big") + msg_pad
        entries.append((sk.pub_key().bytes(), msg, sk.sign(msg)))

    # Host baseline: per-signature OpenSSL verify (ZIP-215 fast path).
    n_base = min(n_sigs, 2000)
    t0 = time.perf_counter()
    ok = all(
        ed25519.verify_zip215_fast(p, m, s) for p, m, s in entries[:n_base]
    )
    host_s = (time.perf_counter() - t0) / n_base
    assert ok

    # Honest batch baseline (VERDICT r3 item 2): host random-linear-
    # combination batch verification — crypto/ed25519/ed25519.go:192-227
    # semantics — implemented natively (Pippenger MSM over 2n points,
    # native/tm_native.cpp ed25519_batch_verify).
    host_batch_rate = 0.0
    try:
        from tendermint_tpu.native import load as _load_native

        _native = _load_native()
        if _native is not None and hasattr(_native, "ed25519_batch_verify"):
            _pubs = b"".join(p for p, _, _ in entries)
            _sigs = b"".join(s for _, _, s in entries)
            _msgs = [m for _, m, _ in entries]
            _native.ed25519_batch_verify(
                _pubs[: 64 * 32], _sigs[: 64 * 64], _msgs[:64]
            )  # warm
            t0 = time.perf_counter()
            ok = _native.ed25519_batch_verify(_pubs, _sigs, _msgs)
            host_batch_rate = n_sigs / (time.perf_counter() - t0)
            assert ok
    except Exception as e:  # noqa: BLE001
        print(f"# host RLC batch baseline failed: {e}", file=sys.stderr)

    # Device path: warm up (compile), then steady-state.
    import numpy as _np

    use_pallas = backend._use_pallas()
    bucket = (
        backend._pallas_bucket(n_sigs) if use_pallas else backend._bucket_for(n_sigs)
    )
    t0 = time.perf_counter()
    res = backend.verify_batch(entries)
    warm = time.perf_counter() - t0
    assert bool(res.all()), "all benchmark signatures must verify"

    # Single cold commit: one synchronous end-to-end verify (prep +
    # transfer + kernel + result readback) through the production batch
    # path. On the relay-attached TPU this pays one full ~65ms round-trip
    # — the latency a lone VerifyCommit call experiences.
    # Span-traced reps: the tracer records host-prep vs device spans so
    # the JSON line carries a per-component breakdown (ISSUE 1 satellite —
    # BENCH_r*.json trajectories get a host/device split, not just a
    # single rate). Record overhead is ~µs on ~100ms ops.
    # TM_TPU_BENCH_TRACE=0 turns the per-rep tracing off; span_summary
    # then honestly reports {"tracing": false} with the stats OMITTED
    # (ISSUE 10 satellite — a 0.0 p50 that means "not measured" poisons
    # every downstream trajectory that averages it).
    from tendermint_tpu.observability import trace as _tr

    trace_on = os.environ.get("TM_TPU_BENCH_TRACE", "1") not in ("", "0")
    if trace_on:
        _tr.TRACER.clear()
        _tr.configure(enabled=True)
    reps = 5 if on_accel else 1
    rep_times = []
    rep_preps = []
    pad_bucket = bucket
    for _ in range(reps):
        prep_t = 0.0
        t0 = time.perf_counter()
        p0 = time.perf_counter()
        if use_pallas and backend._use_rlc():
            from tendermint_tpu.ops import pallas_rlc

            _b, _g, _blk = pallas_rlc.plan_bucket(n_sigs)
            pad_bucket = _b
            with _tr.span("bench.host_prep", n=n_sigs, bucket=_b):
                args = pallas_rlc.prepare_rlc(entries, _b)
            prep_t += time.perf_counter() - p0
            with _tr.span("bench.device", bucket=_b):
                lanes = pallas_rlc.verify_rlc_compact(
                    *args, block=_blk, interpret=not on_accel
                )
            assert bool(lanes.all())
        elif use_pallas:
            from tendermint_tpu.ops import pallas_verify

            with _tr.span("bench.host_prep", n=n_sigs, bucket=bucket):
                args = pallas_verify.prepare_compact(entries, bucket)
            prep_t += time.perf_counter() - p0
            with _tr.span("bench.device", bucket=bucket):
                pallas_verify.verify_compact(*args, interpret=not on_accel)
        else:
            with _tr.span("bench.host_prep", n=n_sigs, bucket=bucket):
                args = backend.prepare_batch_device_hash(entries, bucket)
            prep_t += time.perf_counter() - p0
            kern = backend.ed25519_verify.jitted_verify_device_hash()
            with _tr.span("bench.device", bucket=bucket):
                _np.asarray(kern(*args))
        rep_times.append(time.perf_counter() - t0)
        rep_preps.append(prep_t)
    # median rep: one relay hiccup (tens of ms on a ~100ms op) must not
    # distort the recorded latency figure; prep reports the same median
    # statistic so the printed components stay consistent
    import statistics

    single_s = statistics.median(rep_times) / n_sigs
    prep_med = statistics.median(rep_preps)

    _span_stats = _tr.TRACER.summary() if trace_on else {}
    _tr.configure(enabled=False)
    # host_gil_ms_per_commit: estimated GIL-HELD host milliseconds per
    # n_sigs commit prep — the quantity that bounds concurrent
    # verify_commit throughput (PERF_r05: ~40 ms/commit GIL time vs
    # ~23 ms device time made the host the binding constraint, the
    # EntryBlock representation's target). Estimate = host_prep p50 minus
    # the stages that run GIL-RELEASED in native code (challenges /
    # fused prep) when the native module is loaded; paths without inner
    # spans (prepare_rlc) degrade to the conservative full-prep figure.
    _prep_p50 = _span_stats.get("bench.host_prep", {}).get("p50_ms", 0.0)
    _released_ms = sum(
        _span_stats.get(s, {}).get("p50_ms", 0.0)
        for s in ("ops.challenges", "ops.prep_fused")
    )
    from tendermint_tpu.native import load as _load_native_for_gil

    _gil_ms = _prep_p50 - (
        _released_ms if _load_native_for_gil() is not None else 0.0
    )
    span_summary = {"tracing": False} if not trace_on else {
        "tracing": True,
        "host_prep_ms_p50": round(
            _span_stats.get("bench.host_prep", {}).get("p50_ms", 0.0), 3
        ),
        "host_gil_ms_per_commit": round(max(_gil_ms, 0.0), 3),
        "host_prep_ms_p95": round(
            _span_stats.get("bench.host_prep", {}).get("p95_ms", 0.0), 3
        ),
        "device_ms_p50": round(
            _span_stats.get("bench.device", {}).get("p50_ms", 0.0), 3
        ),
        "device_ms_p95": round(
            _span_stats.get("bench.device", {}).get("p95_ms", 0.0), 3
        ),
        "pad_waste_ratio": round(
            (pad_bucket - n_sigs) / pad_bucket if pad_bucket else 0.0, 4
        ),
        # dispatch-owner split (PR 4): prepared-to-launched wait vs the
        # actual relay occupancy of the single dispatch thread — queue
        # growth shows up here, not as caller convoy on the relay
        "queue_wait_ms_p50": round(
            _span_stats.get("pipeline.queue_wait", {}).get("p50_ms", 0.0), 3
        ),
        "dispatch_relay_ms_p50": round(
            _span_stats.get("pipeline.dispatch", {}).get("p50_ms", 0.0), 3
        ),
    }

    def measure_rtt() -> float:
        """Relay round-trip: a trivial device computation fetched
        synchronously — the irreducible latency floor every synchronous
        call pays, and the bench's relay-health signal."""
        if not on_accel:
            return 0.0
        one = jax.jit(lambda x: x + 1)
        _np.asarray(one(_np.int32(0)))  # warm
        t0 = time.perf_counter()
        for _ in range(3):
            _np.asarray(one(_np.int32(0)))
        return (time.perf_counter() - t0) / 3 * 1e3

    rtt_ms = measure_rtt()

    # Secondary: kernel-only stream (the figure rounds 3-4 reported as the
    # headline) — prep in a helper thread, async dispatch, depth-3
    # in-flight. Kept as `kernel_stream_sigs_per_s`; the HEADLINE below
    # rides types.verify_commit end to end.
    kern_rate = 0.0
    if on_accel and use_pallas and backend._use_rlc():
        # pre-compile every coalesced shape BEFORE any timed stream — a
        # fresh ~25s Mosaic compile inside a timed pass reads as a 20x
        # slowdown (burned round-5 measurement time; keep this first)
        from tendermint_tpu.ops import pallas_rlc as _prw

        for _b in _prw.RLC_BUCKETS:
            _wargs = _prw.prepare_rlc([], _b)
            _prw.verify_rlc_compact(*_wargs)
    if on_accel and use_pallas:
        from concurrent.futures import ThreadPoolExecutor

        if backend._use_rlc():
            from tendermint_tpu.ops import pallas_rlc as _pk

            # the production pipeline coalesces concurrent commits to
            # MAX_SIGS per device batch (flat relay transfer latency);
            # measure the kernel at that same coalesced scale
            k_entries = (entries * ((_pk.MAX_SIGS + n_sigs - 1) // n_sigs))[
                : _pk.MAX_SIGS
            ]
            rlc_bucket, g, blk = _pk.plan_bucket(len(k_entries))
            f = _pk._jitted_rlc_verify(g, blk, False)
            # kernel_stream is the DEVICE capability figure (transfer +
            # execute steady state); host prep at this scale (~230 ms
            # GIL-mixed) is the headline's cost, not the kernel's — so
            # pre-build DISTINCT args per batch (distinct: jax caches
            # transfers per array object, and reused args would measure
            # execute-only) and keep prep out of the timed loop
            n_batches = 4
            pre = [
                _pk.prepare_rlc(k_entries, rlc_bucket) for _ in range(n_batches)
            ]
            prep_fn = None
            kern_sigs = len(k_entries)
        else:
            from tendermint_tpu.ops import pallas_verify as _pk

            f = _pk._jitted_pallas_verify(bucket, _pk.BLOCK, False)
            prep_fn = lambda: _pk.prepare_compact(entries, bucket)  # noqa: E731
            kern_sigs = n_sigs
            n_batches = 8
        with ThreadPoolExecutor(1) as ex:
            t0 = time.perf_counter()
            prep = ex.submit(prep_fn) if prep_fn else None
            inflight = []
            for i in range(n_batches):
                if prep is not None:
                    args = prep.result()
                    if i + 1 < n_batches:
                        prep = ex.submit(prep_fn)
                else:
                    args = pre[i]
                o = f(*args)
                try:
                    o.copy_to_host_async()
                except AttributeError:
                    pass
                inflight.append(o)
                if len(inflight) > 3:
                    assert _np.asarray(inflight.pop(0)).all()
            for o in inflight:
                assert _np.asarray(o).all()
            kern_rate = n_batches * kern_sigs / (time.perf_counter() - t0)

    # HEADLINE: types.verify_commit end to end (VERDICT r4 item 3) — real
    # Commit + ValidatorSet at n_sigs validators, 8 distinct commits
    # streamed through the DEFAULT verification path (sign-bytes
    # composition, seam dispatch, async pipeline, tally, blame), the way a
    # blocksync/consensus node pays it. Relay-health-gated best-of
    # (VERDICT r4 item 4): re-measure when the relay RTT is degraded or
    # attempts disagree, keep every attempt in the log.
    sus_rate = 0.0
    attempts: list = []
    if on_accel and use_pallas:
        try:
            jobs = _build_commit_jobs(n_sigs, n_commits=8)
            sus_rate, attempts = _bench_verify_commit_stream(
                jobs, n_sigs, measure_rtt, traced=trace_on
            )
        except Exception as e:  # noqa: BLE001
            import traceback

            traceback.print_exc()
            print(f"# verify_commit stream bench failed: {e}", file=sys.stderr)
    if attempts:
        # stream-variance accounting (PERF_r06 §4 follow-through): the
        # min/mean/max spread of per-attempt queue-wait and relay
        # occupancy across the stream attempts — a tight spread with
        # queue_wait >> dispatch confirms the single dispatch-owner is
        # pacing the relay; a wide spread refutes it
        def _spread(key):
            vals = [a.get(key, 0.0) for a in attempts]
            return {
                "min": round(min(vals), 3),
                "mean": round(sum(vals) / len(vals), 3),
                "max": round(max(vals), 3),
            }

        span_summary["stream_rate_spread_sigs_per_s"] = _spread("rate")
        # span-derived spreads exist only when the per-attempt tracer ran
        # — with TM_TPU_BENCH_TRACE=0 the keys are OMITTED, not zeroed
        # (downstream consumers key on presence, bench_report tolerates
        # absence)
        if trace_on:
            span_summary["stream_queue_wait_ms_p50"] = _spread(
                "queue_wait_ms_p50"
            )
            span_summary["stream_dispatch_relay_ms_p50"] = _spread(
                "dispatch_relay_ms_p50"
            )
            # overlapped-relay accounting (ISSUE 7): per-attempt H2D time
            # hidden behind device compute, and the overlap ratio spread —
            # the 0.8x-kernel / <=15%-spread acceptance is checkable from
            # this artifact alone
            span_summary["stream_transfer_hidden_ms"] = _spread(
                "transfer_hidden_ms"
            )
            span_summary["stream_overlap_ratio"] = _spread("overlap_ratio")
            # mesh dispatcher (ISSUE 9): per-attempt lane-packing
            # efficiency (all-zero when TM_TPU_MESH is off — the classic
            # dispatcher records no mesh_pack spans)
            span_summary["stream_mesh_lane_occupancy"] = _spread(
                "mesh_lane_occupancy"
            )
            span_summary["stream_mesh_pad_waste_ratio"] = _spread(
                "mesh_pad_waste_ratio"
            )
    dev_s = 1.0 / sus_rate if sus_rate else single_s

    try:
        host_mc = _host_multicore_rate(entries)
    except Exception as e:  # noqa: BLE001
        print(f"# multicore host baseline failed: {e}", file=sys.stderr)
        host_mc = 0.0

    # Print the core result NOW: the driver takes the LAST JSON line, so
    # if a later (secondary) benchmark stalls past the worker timeout the
    # headline number still stands.
    partial = {
        "schema_version": 1,
        "metric": f"verify_commit_{n_sigs}",
        "value": round(1.0 / dev_s, 1),
        "unit": "sigs/s",
        "vs_baseline": round(host_s / dev_s, 3),
        "mode": "verify_commit_stream8" if sus_rate else "single_sync",
        "backend": backend_kind,
        "kernel": ("pallas_rlc" if backend._use_rlc() else "pallas")
        if use_pallas else "xla",
        "host_sigs_per_s": round(1.0 / host_s, 1),
        "host_multicore_sigs_per_s": round(host_mc, 1),
        "host_batch_sigs_per_s": round(host_batch_rate, 1),
        "vs_host_batch": round(1.0 / dev_s / host_batch_rate, 3) if host_batch_rate else 0.0,
        "kernel_vs_host_batch": round(kern_rate / host_batch_rate, 3) if host_batch_rate else 0.0,
        "single_commit_sigs_per_s": round(1.0 / single_s, 1),
        "single_commit_vs_baseline": round(host_s / single_s, 3),
        "relay_rtt_ms": round(rtt_ms, 1),
        "kernel_stream_sigs_per_s": round(kern_rate, 1),
        "stream_attempts": attempts,
        "sustained_sigs_per_s": round(sus_rate, 1),
        "sustained_vs_baseline": round(sus_rate * host_s, 3),
        "span_summary": span_summary,
        "partial": True,
    }
    print(json.dumps(partial), flush=True)

    # BASELINE config #5: pipelined adjacent-header verification
    # (light/verifier.go VerifyAdjacent over a fetched range, signature
    # batches double-buffered on the device via ops.pipeline). A failure
    # here must never discard the primary metric above.
    try:
        hdr_rate = _bench_pipelined_headers(on_accel)
    except Exception as e:  # noqa: BLE001
        print(f"# pipelined-header bench failed: {e}", file=sys.stderr)
        hdr_rate = 0.0

    # BASELINE config #4: mixed-curve batch (ed25519 device lane +
    # sr25519 lane + secp256k1 host). Runs LAST: a hung sr25519 Mosaic
    # compile can wedge the shared relay compile helper, so nothing
    # downstream may depend on it (ops.mixed's watchdog falls back to the
    # host lane after TM_TPU_SR_COMPILE_TIMEOUT).
    mixed_rate = 0.0
    if on_accel:
        try:
            mixed_rate = _bench_mixed_curve()
        except Exception as e:  # noqa: BLE001
            print(f"# mixed-curve bench failed: {e}", file=sys.stderr)

    # Optional closed-loop consensus probe (TM_TPU_BENCH_SIMNET=1): a
    # 4-node simnet cluster — real state machine + reactor + WAL over the
    # virtual network — measured in committed heights per wall second.
    # This exercises the whole host consensus path (sign, gossip, verify,
    # commit), not just the kernel, so it moves when consensus-side work
    # regresses even if the device rate holds.
    simnet_rate = 0.0
    simnet_churn_rate = 0.0
    if os.environ.get("TM_TPU_BENCH_SIMNET"):
        try:
            simnet_rate = _bench_simnet()
        except Exception as e:  # noqa: BLE001
            print(f"# simnet bench failed: {e}", file=sys.stderr)
        try:
            simnet_churn_rate = _bench_simnet_churn()
        except Exception as e:  # noqa: BLE001
            print(f"# simnet churn bench failed: {e}", file=sys.stderr)

    out = {
        "schema_version": 1,
        "metric": f"verify_commit_{n_sigs}",
        "value": round(1.0 / dev_s, 1),
        "unit": "sigs/s",
        "vs_baseline": round(host_s / dev_s, 3),
        "mode": "verify_commit_stream8" if sus_rate else "single_sync",
        "backend": backend_kind,
        "kernel": ("pallas_rlc" if backend._use_rlc() else "pallas")
        if use_pallas else "xla",
        "host_sigs_per_s": round(1.0 / host_s, 1),
        "host_multicore_sigs_per_s": round(host_mc, 1),
        "vs_host_multicore": round(1.0 / dev_s / host_mc, 3) if host_mc else 0.0,
        "host_batch_sigs_per_s": round(host_batch_rate, 1),
        "vs_host_batch": round(1.0 / dev_s / host_batch_rate, 3) if host_batch_rate else 0.0,
        "kernel_vs_host_batch": round(kern_rate / host_batch_rate, 3) if host_batch_rate else 0.0,
        "single_commit_sigs_per_s": round(1.0 / single_s, 1),
        "single_commit_vs_baseline": round(host_s / single_s, 3),
        "relay_rtt_ms": round(rtt_ms, 1),
        "kernel_stream_sigs_per_s": round(kern_rate, 1),
        "stream_attempts": attempts,
        "sustained_sigs_per_s": round(sus_rate, 1),
        "sustained_vs_baseline": round(sus_rate * host_s, 3),
        "mixed_curve_sigs_per_s": round(mixed_rate, 1),
        "pipelined_headers_per_s": round(hdr_rate, 1),
        "simnet_commits_per_s": round(simnet_rate, 2),
        "simnet_churn_commits_per_s": round(simnet_churn_rate, 2),
        "span_summary": span_summary,
    }
    print(json.dumps(out))
    print(
        f"# backend={backend_kind} bucket={bucket} warmup={warm:.1f}s "
        f"host={1.0/host_s:.0f} sigs/s host_mc={host_mc:.0f} sigs/s "
        f"verify_commit_stream={1.0/dev_s:.0f} sigs/s "
        f"kernel_stream={kern_rate:.0f} sigs/s "
        f"single={1.0/single_s:.0f} sigs/s "
        f"rtt={rtt_ms:.0f}ms host_prep={prep_med:.3f}s/batch "
        f"pipelined_headers={hdr_rate:.1f}/s",
        file=sys.stderr,
    )


# ---------------------------------------------------------------------------
# `bench.py multichip` — aggregate sigs/s vs lane count (ISSUE 9 (d)).
# ---------------------------------------------------------------------------


def multichip_main(argv) -> None:
    """Drive CONCURRENT commit streams through the mesh dispatcher at
    increasing lane counts and report the aggregate-throughput linearity
    curve (sigs/s vs lanes), per-lane occupancy and pad waste.

    Default mode is the MOCKED mesh (PERF_r09.md methodology): the real
    lane packing, host prep, transfer and demux machinery runs, but the
    launch returns behind a fixed relay RTT with per-lane compute
    modeled as parallel (an L-device mesh computes its lanes
    concurrently; this box has one device). The curve therefore isolates
    exactly what the mesh dispatcher contributes — signatures packed per
    relay command vs the dispatcher's own serial host costs. `--real`
    launches the actual kernels instead (the TPU-mesh measurement mode;
    on a single CPU device it measures simulated-lane packing against
    real serial compute and the curve flattens accordingly)."""
    import argparse

    ap = argparse.ArgumentParser(prog="bench.py multichip")
    ap.add_argument("--lanes", default="1,2,4",
                    help="comma-separated lane counts for the curve")
    ap.add_argument("--jobs", type=int, default=24,
                    help="concurrent commit-stream jobs per point")
    ap.add_argument("--job-sigs", type=int, default=1024,
                    help="signatures per job (= lane bucket)")
    ap.add_argument("--rtt-ms", type=float, default=60.0,
                    help="mocked relay RTT per superbatch launch")
    ap.add_argument("--reps", type=int, default=2,
                    help="attempts per point (best-of)")
    ap.add_argument("--real", action="store_true",
                    help="launch the real kernels (TPU mesh mode) "
                    "instead of the mocked mesh device")
    ap.add_argument("--hosts", default="",
                    help="fleet scale-out mode (ISSUE 18): comma-separated "
                    "FLEET-HOST counts (e.g. 1,2,4) — one FleetServer + "
                    "verify pipeline per host over real loopback sockets, "
                    "mocked relay, clients round-robined across hosts; "
                    "reports fleet_aggregate_sigs_per_s vs host count "
                    "instead of the mesh-lane curve")
    ap.add_argument("--out", default="",
                    help="also write the JSON artifact to this path")
    args = ap.parse_args(argv)

    if args.hosts:
        return _multichip_fleet(args)

    try:
        import cryptography  # noqa: F401
    except ModuleNotFoundError:
        # mocked-mode entries are random bytes; no real crypto runs
        os.environ.setdefault("TM_TPU_PUREPY_CRYPTO", "1")
    os.environ["TM_TPU_MESH_LANE_BUCKET"] = str(args.job_sigs)

    import numpy as np

    from tendermint_tpu.libs import jaxcache
    import jax

    jaxcache.enable(jax, os.path.dirname(os.path.abspath(__file__)))
    from tendermint_tpu.libs.metrics import ops_stats
    from tendermint_tpu.observability import trace as tr
    from tendermint_tpu.ops import pipeline as pl
    from tendermint_tpu.ops import sharded as _sharded
    from tendermint_tpu.ops._testing import drain_pool, mock_mesh_prepare
    from tendermint_tpu.ops.entry_block import EntryBlock

    rng = np.random.RandomState(3)
    blocks = []
    for t in range(args.jobs):
        n = args.job_sigs
        blocks.append(EntryBlock(
            rng.randint(0, 256, (n, 32), dtype=np.uint8),
            rng.randint(0, 256, (n, 64), dtype=np.uint8),
            bytes(rng.randint(0, 256, 40 * n, dtype=np.uint8)),
            np.arange(0, 40 * (n + 1), 40, dtype=np.int64),
        ))

    orig_prep = pl.AsyncBatchVerifier._prepare_mesh
    if not args.real:
        pl.AsyncBatchVerifier._prepare_mesh = staticmethod(
            mock_mesh_prepare(orig_prep, args.rtt_ms / 1e3)
        )

    def point(lanes: int) -> dict:
        best = None
        for _ in range(max(args.reps, 1)):
            v = pl.AsyncBatchVerifier(depth=3, mesh_lanes=lanes)
            try:
                v.submit(blocks[0][0 : min(64, args.job_sigs)]).result(
                    timeout=600
                )  # warm: compile/trace the shapes off the clock
                # tracing starts AFTER the warm launch so its mesh_pack
                # span does not pollute the timed pass's packing stats
                tr.TRACER.clear()
                tr.configure(enabled=True)
                t0 = time.perf_counter()
                futs = [v.submit(b) for b in blocks]
                for f in futs:
                    f.result(timeout=600)
                dt = time.perf_counter() - t0
                drain_pool(v._pool)
                pool = v._pool.stats()
            finally:
                tr.configure(enabled=False)
                v.close()
            # mesh_pack spans of the timed pass: packing efficiency
            launches = live = total = 0
            lane_buckets = set()
            for name, _s, _e, _tid, sargs in tr.TRACER.events():
                if name != "pipeline.mesh_pack" or not sargs:
                    continue
                launches += 1
                live += int(sargs.get("live", 0))
                total += int(sargs.get("lanes", 0)) * int(
                    sargs.get("lane_bucket", 0)
                )
                lane_buckets.add(int(sargs.get("lane_bucket", 0)))
            s = ops_stats()
            att = {
                "lanes": lanes,
                "sigs_per_s": round(args.jobs * args.job_sigs / dt, 1),
                "wall_s": round(dt, 4),
                "launches": launches,
                # the OBSERVED per-lane bucket(s) — the plan quantizes
                # the lane cap to the ladder, so this can exceed
                # --job-sigs (occupancy below is against this value)
                "lane_bucket": sorted(lane_buckets),
                "mean_occupancy": round(live / total, 4) if total else 0.0,
                "pad_waste_ratio": round(
                    (total - live) / total, 4
                ) if total else 0.0,
                "last_gauge_occupancy": round(
                    s["mesh_lane_occupancy"], 4
                ),
                "pool": pool,
            }
            print(f"# multichip lanes={lanes}: {att['sigs_per_s']:.0f} "
                  f"sigs/s over {launches} launches "
                  f"(occ {att['mean_occupancy']})", file=sys.stderr)
            if best is None or att["sigs_per_s"] > best["sigs_per_s"]:
                best = att
        return best

    try:
        curve = [point(L) for L in
                 sorted({int(x) for x in args.lanes.split(",") if x})]
    finally:
        pl.AsyncBatchVerifier._prepare_mesh = orig_prep

    by_lanes = {c["lanes"]: c["sigs_per_s"] for c in curve}
    base = by_lanes.get(1, curve[0]["sigs_per_s"] if curve else 0.0)
    out = {
        "schema_version": 1,
        "metric": "multichip_aggregate_sigs_per_s",
        "value": curve[-1]["sigs_per_s"] if curve else 0.0,
        "unit": "sigs/s",
        "mode": "real" if args.real else "mocked_mesh",
        "backend": jax.default_backend(),
        "shard_map": _sharded.shard_map_available(),
        "jobs": args.jobs,
        "job_sigs": args.job_sigs,
        "lane_bucket": (curve[-1]["lane_bucket"] if curve else []),
        "mock_rtt_ms": None if args.real else args.rtt_ms,
        "curve": curve,
        "linearity_vs_1_lane": {
            str(k): round(v / base, 3) for k, v in sorted(by_lanes.items())
        } if base else {},
        "speedup_2v1": round(by_lanes.get(2, 0.0) / base, 3) if base else 0.0,
    }
    if not args.real and out["speedup_2v1"] and out["speedup_2v1"] < 1.6:
        print(f"# WARNING: 2-lane aggregate speedup {out['speedup_2v1']} "
              "< 1.6x acceptance bar", file=sys.stderr)
    line = json.dumps(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(json.dumps(out, indent=2) + "\n")
    print(line)


def _multichip_fleet(args) -> None:
    """`bench.py multichip --hosts N`: the verification-fleet scale-out
    curve (ISSUE 18). One FleetServer + its own verify pipeline per
    fleet host, all in this process; eight FleetClient nodes round-robin
    across the hosts over REAL loopback TCP (the full wire codec runs —
    encode, framing, parse, verdict demux). The relay is MOCKED per the
    multichip methodology: real ingress, host prep and transfer, but
    each launch's verdict matures --rtt-ms after launch
    (DeadlineReadback), so the curve isolates what multi-host dispatch
    contributes — independent relay pipelines draining one cluster's
    verify traffic in parallel. Blocks ride at PRIORITY_INGRESS (fleet
    traffic IS network ingress), whose fuse cap keeps launches
    per-block, so host count — not coalescing luck — moves the curve."""
    try:
        import cryptography  # noqa: F401
    except ModuleNotFoundError:
        os.environ.setdefault("TM_TPU_PUREPY_CRYPTO", "1")

    import numpy as np

    from tendermint_tpu.libs import jaxcache
    import jax

    jaxcache.enable(jax, os.path.dirname(os.path.abspath(__file__)))
    from tendermint_tpu.fleet.client import FleetClient
    from tendermint_tpu.fleet.server import FleetServer
    from tendermint_tpu.observability import trace as tr
    from tendermint_tpu.ops import pipeline as pl
    from tendermint_tpu.ops._testing import drain_pool, mock_vote_prepare
    from tendermint_tpu.ops.entry_block import EntryBlock

    rng = np.random.RandomState(7)
    blocks = []
    for t in range(args.jobs):
        n = args.job_sigs
        blocks.append(EntryBlock(
            rng.randint(0, 256, (n, 32), dtype=np.uint8),
            rng.randint(0, 256, (n, 64), dtype=np.uint8),
            bytes(rng.randint(0, 256, 40 * n, dtype=np.uint8)),
            np.arange(0, 40 * (n + 1), 40, dtype=np.int64),
        ))
    n_clients = 8

    orig_prep = pl.AsyncBatchVerifier._prepare
    pl.AsyncBatchVerifier._prepare = staticmethod(
        mock_vote_prepare(orig_prep, args.rtt_ms / 1e3)
    )

    def point(hosts: int) -> dict:
        best = None
        for _ in range(max(args.reps, 1)):
            vs = [pl.AsyncBatchVerifier(depth=3) for _ in range(hosts)]
            srvs = [FleetServer(verifier=v).start() for v in vs]
            clients = [
                FleetClient(srvs[i % hosts].addr, name=f"bench-{i}",
                            lane="bench", timeout_ms=300_000)
                for i in range(n_clients)
            ]
            try:
                # warm every host pipeline and connection off the clock
                for c in clients:
                    c.submit(blocks[0][0:64], flow=1,
                             priority=pl.PRIORITY_INGRESS).result(timeout=600)
                tr.TRACER.clear()
                tr.configure(enabled=True)
                t0 = time.perf_counter()
                futs = [
                    clients[t % n_clients].submit(
                        b, flow=100 + t, priority=pl.PRIORITY_INGRESS)
                    for t, b in enumerate(blocks)
                ]
                for f in futs:
                    f.result(timeout=600)
                dt = time.perf_counter() - t0
                for v in vs:
                    drain_pool(v._pool)
                leaked = sum(v._pool.stats()["in_flight"] for v in vs)
            finally:
                tr.configure(enabled=False)
                for c in clients:
                    c.close()
                for s in srvs:
                    s.stop()
                for v in vs:
                    v.close()
            launches = sum(1 for name, *_ in tr.TRACER.events()
                           if name == "pipeline.dispatch")
            att = {
                "hosts": hosts,
                "clients": n_clients,
                "sigs_per_s": round(args.jobs * args.job_sigs / dt, 1),
                "wall_s": round(dt, 4),
                "launches": launches,
                "pool_leaked": leaked,
            }
            print(f"# multichip --hosts {hosts}: "
                  f"{att['sigs_per_s']:.0f} sigs/s over {launches} "
                  f"launches ({n_clients} clients)", file=sys.stderr)
            if best is None or att["sigs_per_s"] > best["sigs_per_s"]:
                best = att
        return best

    try:
        curve = [point(H) for H in
                 sorted({int(x) for x in args.hosts.split(",") if x})]
    finally:
        pl.AsyncBatchVerifier._prepare = orig_prep

    by_hosts = {c["hosts"]: c["sigs_per_s"] for c in curve}
    base = by_hosts.get(1, curve[0]["sigs_per_s"] if curve else 0.0)
    out = {
        "schema_version": 1,
        "metric": "fleet_aggregate_sigs_per_s",
        "value": curve[-1]["sigs_per_s"] if curve else 0.0,
        "unit": "sigs/s",
        "mode": "real" if args.real else "mocked_fleet_transport",
        "backend": jax.default_backend(),
        "jobs": args.jobs,
        "job_sigs": args.job_sigs,
        "clients": n_clients,
        "mock_rtt_ms": None if args.real else args.rtt_ms,
        "curve": curve,
        "linearity_vs_1_host": {
            str(k): round(v / base, 3) for k, v in sorted(by_hosts.items())
        } if base else {},
        "speedup_2v1": round(
            by_hosts.get(2, 0.0) / base, 3) if base else 0.0,
    }
    if not args.real and out["speedup_2v1"] and out["speedup_2v1"] < 1.6:
        print(f"# WARNING: 2-host aggregate speedup {out['speedup_2v1']} "
              "< 1.6x acceptance bar", file=sys.stderr)
    line = json.dumps(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(json.dumps(out, indent=2) + "\n")
    print(line)


def _build_commit_jobs(n_vals: int, n_commits: int):
    """Real ValidatorSet + n_commits distinct Commits at n_vals validators
    (unique keys, canonical precommit sign-bytes), for the end-to-end
    verify_commit headline. Commits are built directly from signed
    CommitSigs (VoteSet.add_vote would re-verify every vote during
    setup)."""
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.types import Validator, ValidatorSet, Vote
    from tendermint_tpu.types.block import (
        BlockID, Commit, CommitSig, PartSetHeader, BLOCK_ID_FLAG_COMMIT,
    )
    from tendermint_tpu.types.vote import PRECOMMIT_TYPE
    from tendermint_tpu.wire.canonical import Timestamp

    chain_id = "bench-chain"
    sks, vals = [], []
    for i in range(n_vals):
        sk = ed25519.gen_priv_key(i.to_bytes(32, "little"))
        sks.append(sk)
        vals.append(Validator.new(sk.pub_key(), 100))
    vset = ValidatorSet.new(vals)
    by_addr = {v.address: sk for sk, v in zip(sks, vals)}
    ordered = [by_addr[v.address] for v in vset.validators]

    jobs = []
    for h in range(1, n_commits + 1):
        bid = BlockID(
            hash=bytes([h]) * 32,
            part_set_header=PartSetHeader(total=1, hash=bytes([h]) * 32),
        )
        ts = Timestamp(seconds=1_600_000_000 + h)
        sigs = []
        for idx, sk in enumerate(ordered):
            v = Vote(
                type=PRECOMMIT_TYPE, height=h, round=0, block_id=bid,
                timestamp=ts,
                validator_address=vset.validators[idx].address,
                validator_index=idx,
            )
            sigs.append(
                CommitSig(
                    block_id_flag=BLOCK_ID_FLAG_COMMIT,
                    validator_address=vset.validators[idx].address,
                    timestamp=ts,
                    signature=sk.sign(v.sign_bytes(chain_id)),
                )
            )
        commit = Commit(height=h, round=0, block_id=bid, signatures=sigs)
        jobs.append((chain_id, vset, bid, h, commit))
    return jobs


def _bench_verify_commit_stream(jobs, n_sigs: int, measure_rtt,
                                traced: bool = True) -> tuple:
    """Stream the commits through types.verify_commit concurrently (their
    device batches pipeline through the shared AsyncBatchVerifier) and
    return (best_rate, attempts). Relay-health gating: retry when the RTT
    exceeds RTT_HEALTHY_MS or the attempt disagrees with the best by >15%
    — one bad-luck relay window must not record a 2x-low number.

    Each attempt runs span-traced (cleared per pass) and carries its OWN
    queue_wait_ms_p50 / dispatch_relay_ms_p50 — the per-attempt numbers
    PERF_r06 §4 deferred, so the dispatch-owner fix is confirmed (or
    refuted) by the attempt-to-attempt spread, not a single aggregate."""
    from concurrent.futures import ThreadPoolExecutor

    from tendermint_tpu.observability import trace as _tr
    from tendermint_tpu.types import validation as _val

    RTT_HEALTHY_MS = float(os.environ.get("TM_TPU_BENCH_RTT_HEALTHY_MS", "90"))
    MIN_ATTEMPTS = int(os.environ.get("TM_TPU_BENCH_STREAM_MIN_ATTEMPTS", "3"))
    MAX_ATTEMPTS = int(os.environ.get("TM_TPU_BENCH_STREAM_ATTEMPTS", "5"))

    def clear_caches() -> None:
        # per-commit sign-bytes template + hash caches: the timed pass
        # must pay the real host composition cost exactly once per commit
        for _, _, _, _, commit in jobs:
            commit._sb_tpl = None
            commit._hash = None

    def transfer_overlap(trace_doc: dict) -> tuple:
        """(hidden_ms, total_ms) over the pass's pipeline.transfer spans
        — hidden=1 marks copies issued while a kernel was in flight."""
        hidden = total = 0.0
        for ev in trace_doc.get("traceEvents", []):
            if ev.get("name") != "pipeline.transfer":
                continue
            dur = float(ev.get("dur", 0.0)) / 1e3
            total += dur
            if (ev.get("args") or {}).get("hidden"):
                hidden += dur
        return hidden, total

    def mesh_pack_stats(trace_doc: dict) -> tuple:
        """(occupancy, pad_waste) over the pass's pipeline.mesh_pack
        spans — (0, 0) when the mesh dispatcher is off (TM_TPU_MESH
        unset). ISSUE 9 satellite: per-attempt lane-packing efficiency
        rides the stream artifact next to the overlap ratios."""
        live = total = 0
        for ev in trace_doc.get("traceEvents", []):
            if ev.get("name") != "pipeline.mesh_pack":
                continue
            a = ev.get("args") or {}
            live += int(a.get("live", 0))
            total += int(a.get("lanes", 0)) * int(a.get("lane_bucket", 0))
        if not total:
            return 0.0, 0.0
        return live / total, (total - live) / total

    def one_pass(traced: bool = False) -> tuple:
        clear_caches()
        if traced:
            _tr.TRACER.clear()
            _tr.configure(enabled=True)
        try:
            with ThreadPoolExecutor(len(jobs)) as ex:
                t0 = time.perf_counter()
                futs = [
                    ex.submit(_val.verify_commit, cid, vs, bid, h, cm)
                    for cid, vs, bid, h, cm in jobs
                ]
                for f in futs:
                    f.result()  # raises on any verification failure
                rate = len(jobs) * n_sigs / (time.perf_counter() - t0)
        finally:
            if traced:
                doc = _tr.TRACER.export_chrome()
                spans = _tr.summarize_events(doc)
                spans["_transfer_overlap"] = transfer_overlap(doc)
                spans["_mesh_pack"] = mesh_pack_stats(doc)
                _tr.configure(enabled=False)
            else:
                spans = {}
        return rate, spans

    one_pass()  # warm: compiles shapes, fills ValidatorSet-level caches
    attempts = []
    for attempt in range(MAX_ATTEMPTS):
        import gc

        gc.collect()  # each pass churns ~100 MB of entry tuples/arrays;
        # collect OUTSIDE the timed window, not during it
        rtt = measure_rtt()
        rate, spans = one_pass(traced=traced)
        att = {"rate": round(rate, 1), "rtt_ms": round(rtt, 1)}
        if traced:
            hidden_ms, transfer_ms = spans.get(
                "_transfer_overlap", (0.0, 0.0)
            )
            occ, pad = spans.get("_mesh_pack", (0.0, 0.0))
            att.update({
                "mesh_lane_occupancy": round(occ, 4),
                "mesh_pad_waste_ratio": round(pad, 4),
                "queue_wait_ms_p50": round(
                    spans.get("pipeline.queue_wait", {}).get("p50_ms", 0.0),
                    3,
                ),
                "dispatch_relay_ms_p50": round(
                    spans.get("pipeline.dispatch", {}).get("p50_ms", 0.0), 3
                ),
                # overlapped relay (ISSUE 7): how much of this attempt's
                # H2D time rode behind device compute
                "transfer_ms": round(transfer_ms, 3),
                "transfer_hidden_ms": round(hidden_ms, 3),
                "overlap_ratio": round(
                    hidden_ms / transfer_ms if transfer_ms else 0.0, 4
                ),
            })
        attempts.append(att)
        print(f"# verify_commit stream attempt {attempt}: {rate:.0f} sigs/s "
              f"(rtt {rtt:.0f}ms)", file=sys.stderr)
        # best-of over >= MIN_ATTEMPTS passes: batch splits and GIL
        # scheduling are nondeterministic, so single passes scatter.
        # Extra passes (up to MAX) while the relay looks unhealthy OR the
        # recent passes still disagree by >15%.
        if len(attempts) >= MIN_ATTEMPTS and rtt <= RTT_HEALTHY_MS:
            recent = [a["rate"] for a in attempts[-MIN_ATTEMPTS:]]
            if max(recent) - min(recent) <= 0.15 * max(recent):
                break
    return max(a["rate"] for a in attempts), attempts


def _bench_mixed_curve() -> float:
    """Mixed 4k set: 2048 ed25519 + 1792 sr25519 + 256 secp256k1 through
    ops.mixed.verify_mixed — the three lanes run concurrently (ed future
    on the shared pipeline + sr device thread + secp host loop), so the
    batch costs max(lanes), not sum. sr25519 signing is pure-Python
    ~10 ms/sig; the set is sized to keep generation inside the worker
    budget."""
    # tight sr-compile budget at bench time: a hung Mosaic compile must
    # not eat the worker window (ops.mixed falls back to the host lane)
    os.environ.setdefault("TM_TPU_SR_COMPILE_TIMEOUT", "120")
    from tendermint_tpu.crypto import ed25519, secp256k1, sr25519
    from tendermint_tpu.ops.mixed import verify_mixed

    entries = []
    for i in range(2048):
        sk = ed25519.gen_priv_key(i.to_bytes(32, "little"))
        m = b"mx-ed-%d" % i
        entries.append((sk.pub_key(), m, sk.sign(m)))
    srk = sr25519.gen_priv_key(b"\x09" * 32)
    for i in range(1792):
        m = b"mx-sr-%d" % i
        entries.append((srk.pub_key(), m, srk.sign(m)))
    sck = secp256k1.gen_priv_key()
    for i in range(256):
        m = b"mx-secp-%d" % i
        entries.append((sck.pub_key(), m, sck.sign(m)))
    import random

    random.Random(5).shuffle(entries)
    res = verify_mixed(entries)  # warm (compiles both device lanes)
    assert all(res), "mixed batch must verify"
    t0 = time.perf_counter()
    res = verify_mixed(entries)
    dt = time.perf_counter() - t0
    return len(entries) / dt


def _bench_simnet(height: int = 15) -> float:
    """simnet throughput probe: 4 real consensus nodes, fixed seed,
    default links, run to `height`; committed heights per wall second."""
    from tendermint_tpu.simnet import Cluster

    cluster = Cluster(n_nodes=4, seed=1)
    try:
        rep = cluster.run_to_height(height, max_virtual_s=600.0)
    finally:
        cluster.stop()  # closes WALs and removes the temp dir even on error
    if not rep.ok or rep.wall_s <= 0:
        return 0.0
    return rep.height / rep.wall_s


def _bench_simnet_churn(height: int = 15) -> float:
    """Rotation variant of the simnet probe: 6 nodes / 4 active
    validators with a join+leave churn every 4 heights, so the measured
    path includes EndBlock validator updates, valset-hash invalidation
    and (when enabled) epoch-cache cold/warm cycling. Heights per wall
    second; 0.0 when the run goes red."""
    from tendermint_tpu.simnet import Cluster, rotation_schedule

    faults = rotation_schedule(6, 4, every=4, start=3, until=height - 4)
    cluster = Cluster(n_nodes=6, n_validators=4, seed=1, faults=faults)
    try:
        rep = cluster.run_to_height(height, max_virtual_s=600.0)
    finally:
        cluster.stop()
    if not rep.ok or rep.wall_s <= 0:
        return 0.0
    return rep.height / rep.wall_s


def _build_header_chain(chain_id: str, n_headers: int, n_vals: int):
    """Synthetic adjacent signed-header chain over one validator set —
    shared by the pipelined-header benchmark and `bench.py light`.
    Returns [(SignedHeader, ValidatorSet), ...] of length n_headers + 1
    (index 0 is the root of trust)."""
    from dataclasses import replace as _dc_replace

    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.types import SignedHeader, Validator, ValidatorSet, Vote
    from tendermint_tpu.types.block import BlockID, Header, PartSetHeader, Version
    from tendermint_tpu.types.vote import PRECOMMIT_TYPE
    from tendermint_tpu.types.vote_set import VoteSet
    from tendermint_tpu.wire.canonical import Timestamp

    sks, vals = [], []
    for i in range(n_vals):
        sk = ed25519.gen_priv_key((i + 7).to_bytes(32, "little"))
        sks.append(sk)
        vals.append(Validator.new(sk.pub_key(), 100))
    vset = ValidatorSet.new(vals)
    by_addr = {v.address: sk for sk, v in zip(sks, vals)}
    ordered = [by_addr[v.address] for v in vset.validators]

    shs = []
    prev_hash = b"\x00" * 32
    for h in range(1, n_headers + 2):
        hdr = Header(
            version=Version(block=11, app=0), chain_id=chain_id, height=h,
            time=Timestamp(seconds=1_600_000_000 + h),
            last_block_id=BlockID(
                hash=prev_hash, part_set_header=PartSetHeader(total=1, hash=prev_hash)
            ) if h > 1 else BlockID(),
            validators_hash=vset.hash(), next_validators_hash=vset.hash(),
            consensus_hash=b"\x01" * 32, app_hash=b"",
            proposer_address=vset.validators[0].address,
        )
        bid = BlockID(hash=hdr.hash(), part_set_header=PartSetHeader(total=1, hash=hdr.hash()))
        vs = VoteSet(chain_id, h, 0, PRECOMMIT_TYPE, vset)
        for idx, sk in enumerate(ordered):
            v = Vote(
                type=PRECOMMIT_TYPE, height=h, round=0, block_id=bid,
                timestamp=Timestamp(seconds=1_600_000_000 + h),
                validator_address=vset.validators[idx].address, validator_index=idx,
            )
            v = _dc_replace(v, signature=sk.sign(v.sign_bytes(chain_id)))
            vs.add_vote(v)
        shs.append((SignedHeader(header=hdr, commit=vs.make_commit()), vset))
        prev_hash = hdr.hash()
    return shs


def _bench_pipelined_headers(on_accel: bool) -> float:
    """Build a synthetic adjacent header chain and measure pipelined
    verification throughput (headers/s, steady-state after warmup)."""
    from tendermint_tpu.ops import pipeline as _pl

    n_headers = int(os.environ.get("TM_TPU_BENCH_HEADERS", "1000" if on_accel else "32"))
    n_vals = int(os.environ.get("TM_TPU_BENCH_HEADER_VALS", "128" if on_accel else "8"))
    chain_id = "bench-chain"
    shs = _build_header_chain(chain_id, n_headers, n_vals)

    trusted = shs[0][0]
    # warm pass compiles the full-bucket kernel shape (the 10240-lane
    # compile is ~11s/process even with the persistent cache); the timed
    # pass is steady state with all per-commit caches cleared so every
    # header pays its real sign-bytes/hashing cost exactly once
    _pl.verify_headers_pipelined(chain_id, trusted, shs[1:])
    for sh, _ in shs:
        sh.commit._sb_tpl = None
        sh.commit._hash = None
    t0 = time.perf_counter()
    _pl.verify_headers_pipelined(chain_id, trusted, shs[1:])
    dt = time.perf_counter() - t0
    return (len(shs) - 1) / dt


def light_main(argv) -> None:
    """`bench.py light` — the light-service serving benchmark (ISSUE 11):
    C simulated clients each requesting skipping verification of H
    headers (one warm epoch — the trust-period shape both light-client
    papers observe), driven through LightVerifyService over the real
    pipeline with the device mocked behind a fixed relay RTT (the
    --overlap/multichip mock philosophy: real host prep, epoch grouping,
    coalescing and transfer; the launch returns an all-accept verdict
    row behind rtt_ms). Headline: delivered header verdicts/s across the
    client fleet. Honest secondary figures: the UNIQUE-verification rate
    (client 1's cold pass — no request-level dedup), the sequential
    per-request baseline on the same mocked engine, and the memo hit
    ratio. `--real` runs live kernels instead of the mock (TPU runs).

    Prints ONE JSON line; --out also writes it as an artifact file
    (LIGHT_r*.json, schema_version 1, rendered by tools/bench_report.py
    --trajectory and gated by --compare)."""
    import argparse

    ap = argparse.ArgumentParser(prog="bench.py light")
    ap.add_argument("--clients", type=int, default=256,
                    help="simulated light clients (default 256)")
    ap.add_argument("--headers", type=int, default=48,
                    help="target headers per client (default 48)")
    ap.add_argument("--vals", type=int, default=32,
                    help="validators per set (default 32)")
    ap.add_argument("--rtt-ms", type=float, default=60.0,
                    help="mocked relay round-trip per launch (default 60)")
    ap.add_argument("--real", action="store_true",
                    help="run live kernels instead of the mocked relay")
    ap.add_argument("--out", default="",
                    help="also write the artifact JSON to this path")
    args = ap.parse_args(argv)

    from tendermint_tpu.libs import jaxcache

    import jax

    jaxcache.enable(jax, os.path.dirname(os.path.abspath(__file__)))

    from tendermint_tpu.light import verifier as _lv
    from tendermint_tpu.light.batch import HeaderRequest
    from tendermint_tpu.light.service import LightVerifyService
    from tendermint_tpu.ops import pipeline as _pl
    from tendermint_tpu.ops._testing import mock_light_prepare
    from tendermint_tpu.ops import epoch_cache as _epoch
    from tendermint_tpu.wire.canonical import Timestamp

    chain_id = "light-bench"
    print(f"# building {args.headers + 1}-header chain, "
          f"{args.vals} validators", file=sys.stderr)
    shs = _build_header_chain(chain_id, args.headers, args.vals)
    trusted, vset = shs[0]
    now = Timestamp(seconds=1_600_000_000 + len(shs) + 60)
    period = 1e9

    def requests_for_client(_c: int):
        # every client skip-verifies the same published chain from the
        # same root of trust — the serving shape the papers motivate
        return [
            HeaderRequest(
                trusted_header=trusted, trusted_vals=vset,
                untrusted_header=shs[k][0], untrusted_vals=shs[k][1],
                trusting_period=period,
            )
            for k in range(1, args.headers + 1)
        ]

    _epoch.reset(8)  # warm-epoch methodology: device tables amortize
    real_prepare = _pl.AsyncBatchVerifier._prepare
    if not args.real:
        _pl.AsyncBatchVerifier._prepare = staticmethod(
            mock_light_prepare(real_prepare, args.rtt_ms / 1e3)
        )
    v = _pl.AsyncBatchVerifier(depth=3)
    svc = LightVerifyService(verifier=v, memo_size=4 * args.headers)
    try:
        # cold pass (client 1): every request is a unique verification —
        # host prep + epoch grouping + coalescing, no request-level dedup
        t0 = time.perf_counter()
        svc.submit_many(requests_for_client(0), now=now).results(timeout=900)
        unique_rate = args.headers / (time.perf_counter() - t0)
        # warm fleet: C clients re-request the same trust window
        t0 = time.perf_counter()
        batches = [
            svc.submit_many(requests_for_client(c), now=now)
            for c in range(1, args.clients)
        ]
        n_done = sum(len(b.results(timeout=900)) for b in batches)
        dt = time.perf_counter() - t0
        rate = n_done / dt
        stats = svc.stats()

        # sequential per-request baseline on the SAME engine: one
        # verifier.verify call per header, no cross-request anything.
        # TM_TPU_FORCE_DEVICE routes the sub-threshold commit sizes
        # through the (mocked) device engine too, so both columns pay
        # the same relay cost model — per-request dispatch pays the RTT
        # per stage, which is exactly the ~1.2k headers/s ceiling the
        # service removes (without it the baseline silently measures
        # host-crypto speed instead).
        seq_n = min(args.headers, 16)
        os.environ["TM_TPU_FORCE_DEVICE"] = "1"
        try:
            t0 = time.perf_counter()
            for k in range(1, seq_n + 1):
                _lv.verify(trusted, vset, shs[k][0], shs[k][1], period, now,
                           10.0, _lv.DEFAULT_TRUST_LEVEL)
            seq_rate = seq_n / (time.perf_counter() - t0)
        finally:
            os.environ.pop("TM_TPU_FORCE_DEVICE", None)
    finally:
        svc.close()
        v.close()
        _pl.AsyncBatchVerifier._prepare = real_prepare

    out = {
        "schema_version": 1,
        "metric": "light_service_headers_per_s",
        "value": round(rate, 1),
        "unit": "headers/s",
        "mode": "real" if args.real else "mocked-relay",
        "backend": os.environ.get("JAX_PLATFORMS", "") or "cpu",
        "light_clients": args.clients,
        "headers_per_client": args.headers,
        "vals_per_set": args.vals,
        "relay_rtt_ms": args.rtt_ms if not args.real else None,
        "light_unique_headers_per_s": round(unique_rate, 1),
        "light_sequential_headers_per_s": round(seq_rate, 1),
        "vs_sequential": round(rate / seq_rate, 2) if seq_rate else None,
        "memo_hit_ratio": round(
            stats["memo_hits"] / max(stats["requests"], 1), 4
        ),
        "unique_verifications": stats["unique"],
        "requests": stats["requests"],
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1)
            fh.write("\n")


def _p99_ms(samples_s) -> float:
    xs = sorted(samples_s)
    if not xs:
        return 0.0
    return xs[min(int(round(0.99 * (len(xs) - 1))), len(xs) - 1)] * 1e3


def mempool_main(argv) -> None:
    """`bench.py mempool` — device-batched transaction ingress (ISSUE 13).

    Floods signed txs through the FULL CheckTx path (envelope parse,
    seen-cache, batched device signature verdict, nonce, app CheckTx)
    with the device mocked behind a fixed per-launch relay RTT
    (mock_mempool_prepare — real accumulation, EntryBlock packing, host
    prep and transfer; the launch's verdict matures rtt_ms after launch).
    Headline: CheckTx signature verdicts/s through the windowed
    accumulator. The honest baseline is the SAME mocked engine driven
    per-tx (window=0, batch=1 — one relay launch per tx, the shape
    CheckTx had before the accumulator), under the TM_TPU_FORCE_DEVICE
    discipline so neither column quietly routes to host crypto.

    QoS figure: consensus-priority commit batches run back-to-back
    unloaded, then again under a sustained ingress flood — the artifact
    records both p99s and their ratio (the ISSUE 13 bound: within 2x),
    plus the preemption count the priority queue logged while consensus
    overtook queued tx superbatches.

    Prints ONE JSON line; --out also writes it as an artifact file
    (MEMPOOL_r*.json, schema_version 1, rendered by tools/bench_report.py
    --trajectory and gated by --compare)."""
    import argparse
    import threading

    ap = argparse.ArgumentParser(prog="bench.py mempool")
    ap.add_argument("--txs", type=int, default=4096,
                    help="signed txs in the flood (default 4096)")
    ap.add_argument("--senders", type=int, default=64,
                    help="distinct sender keys (default 64)")
    ap.add_argument("--batch", type=int, default=512,
                    help="accumulator max batch (default 512)")
    ap.add_argument("--window-ms", type=float, default=4.0,
                    help="accumulator window (default 4)")
    ap.add_argument("--rtt-ms", type=float, default=40.0,
                    help="mocked relay round-trip per launch (default 40)")
    ap.add_argument("--commits", type=int, default=100,
                    help="consensus commit batches per column (default 100)")
    ap.add_argument("--commit-sigs", type=int, default=128,
                    help="signatures per commit batch (default 128)")
    ap.add_argument("--seq-txs", type=int, default=48,
                    help="txs for the per-tx baseline (default 48)")
    ap.add_argument("--real", action="store_true",
                    help="run live kernels instead of the mocked relay")
    ap.add_argument("--out", default="",
                    help="also write the artifact JSON to this path")
    args = ap.parse_args(argv)

    from tendermint_tpu.libs import jaxcache

    import jax

    jaxcache.enable(jax, os.path.dirname(os.path.abspath(__file__)))

    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.crypto import ed25519 as _ed
    from tendermint_tpu.mempool import TxMempool
    from tendermint_tpu.mempool import ingress as _ing
    from tendermint_tpu.ops import epoch_cache as _epoch
    from tendermint_tpu.ops import pipeline as _pl
    from tendermint_tpu.ops._testing import mock_mempool_prepare
    from tendermint_tpu.ops.entry_block import EntryBlock

    print(f"# signing {args.txs} txs from {args.senders} senders",
          file=sys.stderr)
    import hashlib as _hashlib

    privs = [
        _ed.gen_priv_key(
            seed=_hashlib.sha256(b"mempool-bench-%d" % s).digest()
        )
        for s in range(args.senders)
    ]
    txs = [
        _ing.make_signed_tx(
            privs[i % args.senders],
            b"bench_k%d=v%d" % (i, i),
            nonce=i // args.senders + 1,
        )
        for i in range(args.txs)
    ]
    stxs = [_ing.parse_signed_tx(tx) for tx in txs]
    # the consensus lane's payload: one commit-shaped ed25519 batch,
    # resubmitted per "height" at PRIORITY_CONSENSUS
    commit_block = EntryBlock.from_entries([
        (s.pub, s.signed_bytes(), s.sig)
        for s in stxs[: args.commit_sigs]
    ])

    _epoch.reset(8)
    real_prepare = _pl.AsyncBatchVerifier._prepare
    if not args.real:
        _pl.AsyncBatchVerifier._prepare = staticmethod(
            mock_mempool_prepare(real_prepare, args.rtt_ms / 1e3)
        )
    # both columns under the force-device discipline: nothing below may
    # quietly route a small batch to host crypto and skip the relay cost
    os.environ["TM_TPU_FORCE_DEVICE"] = "1"
    # purepy host crypto makes every pipeline stage a CPU-bound Python
    # thread; the default 5 ms GIL switch interval lets those threads
    # convoy for 100+ ms, which lands on the QoS latency tail as pure
    # interpreter-scheduler noise. Pin 1 ms for the run (restored in
    # the finally) so the columns measure the pipeline, not the GIL.
    _swi = sys.getswitchinterval()
    sys.setswitchinterval(0.001)
    v = _pl.AsyncBatchVerifier(depth=3)
    acc = _ing.IngressAccumulator(
        verifier=v, max_batch=args.batch, window_ms=args.window_ms
    )

    def fresh_mempool(ingress):
        from tendermint_tpu.config import MempoolConfig

        cfg = MempoolConfig()
        cfg.size = max(cfg.size, args.txs * 2)
        cfg.max_txs_bytes = max(cfg.max_txs_bytes, args.txs * 4096)
        return TxMempool(
            LocalClient(KVStoreApplication()), config=cfg, ingress=ingress
        )

    def commit_column(n):
        lats = []
        for _ in range(n):
            t0 = time.perf_counter()
            v.submit(
                commit_block, priority=_pl.PRIORITY_CONSENSUS
            ).result(timeout=300)
            lats.append(time.perf_counter() - t0)
        return lats

    try:
        # -- column A: the headline — flood through full CheckTx ---------
        mp = fresh_mempool(acc)
        t0 = time.perf_counter()
        futs = [mp.check_tx_async(tx) for tx in txs]
        n_ok = sum(1 for f in futs if f.result(timeout=300).is_ok())
        dt = time.perf_counter() - t0
        rate = len(futs) / dt
        if n_ok != len(futs):
            print(f"# WARNING: {len(futs) - n_ok} floods rejected",
                  file=sys.stderr)
        windows_a = acc.batches

        # column A leaves ~`txs` response futures and a fully-loaded
        # mempool behind; a gen-2 GC pass over that heap mid-commit is a
        # 50+ ms pause attributed to the wrong column. Drop both, collect
        # once, and freeze the survivors out of the collector before the
        # latency columns (unfrozen in the finally).
        import gc

        del futs, mp
        gc.collect()
        gc.freeze()

        # -- column B: consensus commits, unloaded -----------------------
        p99_unloaded = _p99_ms(commit_column(args.commits))

        # -- column C: the same commit cadence under sustained flood -----
        # the flood driver resubmits the pre-signed pool straight into
        # the accumulator (device pressure is the contended resource;
        # the mempool's dedup cache would starve a tx-level loop)
        stop = threading.Event()
        flood_sigs = [0]

        def flood():
            # one pool pass outstanding at a time: ~txs/batch windows
            # queued (well past the pipeline depth — real contention)
            # without letting the backlog grow unboundedly
            while not stop.is_set():
                last = None
                for s in stxs:
                    if stop.is_set():
                        break
                    last = acc.submit(s)
                    flood_sigs[0] += 1
                acc.flush_now()
                if last is not None:
                    try:
                        last.result(timeout=300)
                    except Exception:  # noqa: BLE001 — pressure, not verdicts
                        pass

        ft = threading.Thread(target=flood, daemon=True)
        ft.start()
        time.sleep(args.window_ms / 1e3 * 4)  # let the queue build
        flood_lats = commit_column(args.commits)
        stop.set()
        ft.join(timeout=30)
        acc.flush_now()
        p99_flood = _p99_ms(flood_lats)

        # -- baseline: per-tx dispatch on the SAME mocked engine ---------
        seq_acc = _ing.IngressAccumulator(
            verifier=v, max_batch=1, window_ms=0.0
        )
        try:
            mp_seq = fresh_mempool(seq_acc)
            seq_n = min(args.seq_txs, len(txs))
            t0 = time.perf_counter()
            for tx in txs[:seq_n]:
                mp_seq.check_tx(tx)
            seq_rate = seq_n / (time.perf_counter() - t0)
        finally:
            seq_acc.close()
        stats = acc.stats()
    finally:
        acc.close()
        v.close()
        sys.setswitchinterval(_swi)
        os.environ.pop("TM_TPU_FORCE_DEVICE", None)
        _pl.AsyncBatchVerifier._prepare = real_prepare
        import gc

        gc.unfreeze()

    out = {
        "schema_version": 1,
        "metric": "mempool_checktx_sigs_per_s",
        "value": round(rate, 1),
        "unit": "sigs/s",
        "mode": "real" if args.real else "mocked-relay",
        "backend": os.environ.get("JAX_PLATFORMS", "") or "cpu",
        "txs": args.txs,
        "senders": args.senders,
        "ingress_batch": args.batch,
        "ingress_window_ms": args.window_ms,
        "relay_rtt_ms": args.rtt_ms if not args.real else None,
        "mempool_seq_sigs_per_s": round(seq_rate, 1),
        "vs_sequential": round(rate / seq_rate, 2) if seq_rate else None,
        "commit_p99_unloaded_ms": round(p99_unloaded, 2),
        "commit_p99_flood_ms": round(p99_flood, 2),
        "flood_latency_ratio": (
            round(p99_flood / p99_unloaded, 2) if p99_unloaded else None
        ),
        "checktx_preemptions": stats["preemptions"],
        "ingress_windows": windows_a,
        "ingress_batch_wait_ms_avg": round(stats["batch_wait_ms_avg"], 2),
        "flood_sigs_submitted": flood_sigs[0],
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1)
            fh.write("\n")


def _replay_bench_valsets(n_vals: int, n_sets: int):
    """Cycle of distinct validator sets for the replay bench chain —
    real keys (host prep hashes the real pubkeys), one set per rotation
    epoch class. Returns [(ValidatorSet, vals_hash, proposer_addr)]."""
    import hashlib as _hashlib

    from tendermint_tpu.crypto import ed25519 as _ed
    from tendermint_tpu.types.validator_set import Validator, ValidatorSet

    sets = []
    for s in range(n_sets):
        vals = [
            Validator.new(
                _ed.gen_priv_key(
                    seed=_hashlib.sha256(
                        b"replay-bench-%d-%d" % (s, i)
                    ).digest()
                ).pub_key(),
                100,
            )
            for i in range(n_vals)
        ]
        vset = ValidatorSet.new(vals)
        sets.append((vset, vset.hash(), vset.validators[0].address))
    return sets


def _replay_bench_chain(chain_id: str, vsets, rotate: int, rng):
    """Infinite generator of consecutive fully-linked blocks with FAKE
    commit signatures (the simnet rotation-schedule shape: validator
    set cycles every `rotate` heights). The mocked relay returns
    all-accept verdicts, so the signature bytes are never checked —
    everything the replay engine actually pays for is real: block
    encode, part sets, block-id binding, per-signature sign-bytes prep,
    epoch cuts and range packing."""
    from tendermint_tpu.types.block import (
        BLOCK_ID_FLAG_COMMIT,
        Block,
        BlockID,
        Commit,
        CommitSig,
        Data,
        Header,
        Version,
    )
    from tendermint_tpu.types.part_set import BLOCK_PART_SIZE_BYTES, PartSet
    from tendermint_tpu.wire.canonical import Timestamp

    def at(h):
        return vsets[((h - 1) // rotate) % len(vsets)]

    ts0 = Timestamp(seconds=1_600_000_000, nanos=0)
    last_commit, prev_bid = None, BlockID()
    h = 1
    while True:
        vset, vhash, proposer = at(h)
        hdr = Header(
            version=Version(block=11, app=0), chain_id=chain_id, height=h,
            time=Timestamp(seconds=1_600_000_000 + h),
            last_block_id=prev_bid,
            validators_hash=vhash, next_validators_hash=at(h + 1)[1],
            consensus_hash=b"\x01" * 32, app_hash=b"",
            proposer_address=proposer,
        )
        block = Block(header=hdr, data=Data(), last_commit=last_commit)
        block.fill_header()
        parts = PartSet.from_data(block.encode(), BLOCK_PART_SIZE_BYTES)
        bid = BlockID(hash=block.hash(), part_set_header=parts.header())
        last_commit = Commit(
            height=h, round=0, block_id=bid,
            signatures=[
                CommitSig(
                    block_id_flag=BLOCK_ID_FLAG_COMMIT,
                    validator_address=val.address,
                    timestamp=ts0, signature=rng.randbytes(64),
                )
                for val in vset.validators
            ],
        )
        prev_bid = bid
        yield block
        h += 1


def blocksync_main(argv) -> None:
    """`bench.py blocksync` — chain-replay catch-up (ISSUE 14).

    Replays a ≥100k-height synthetic chain with the simnet rotation
    shape (validator set rotates every ~50 heights) through the
    ReplayEngine with the device mocked behind a fixed per-launch relay
    RTT (mock_mempool_prepare — real epoch cuts, range packing, host
    sign-bytes prep, EntryBlock coalescing and transfer; the launch's
    all-accept verdict matures rtt_ms after launch). Chain synthesis is
    the fetch stand-in and runs OFF the clock; the headline times only
    what the engine does with a full block window in hand.

    Headline: replayed heights/s. Honest columns: the per-height
    baseline on the SAME mocked engine (one launch per height — the
    verify-one-ahead shape replay replaces), and the kernel-serial rate
    (heights / (launches x RTT): what the relay alone would cost if the
    host pipelined perfectly — the ISSUE 14 bound is >= 0.5x of it).

    QoS figure: consensus-priority commit batches unloaded vs under a
    sustained replay-priority flood (the rejoining-node scenario: a
    peer catching up must not move live consensus p99 — PR 12's ratio
    methodology at the new PRIORITY_REPLAY tier).

    Prints ONE JSON line; --out also writes it as an artifact file
    (BLOCKSYNC_r*.json, schema_version 1, rendered by
    tools/bench_report.py --trajectory and gated by --compare)."""
    import argparse
    import random
    import threading

    ap = argparse.ArgumentParser(prog="bench.py blocksync")
    ap.add_argument("--heights", type=int, default=100_000,
                    help="heights to replay (default 100000)")
    ap.add_argument("--vals", type=int, default=32,
                    help="validators per set (default 32)")
    ap.add_argument("--val-sets", type=int, default=4,
                    help="distinct validator sets cycled (default 4)")
    ap.add_argument("--rotate", type=int, default=50,
                    help="heights per valset epoch (default 50)")
    ap.add_argument("--window", type=int, default=256,
                    help="replay window in heights (default 256)")
    ap.add_argument("--rtt-ms", type=float, default=40.0,
                    help="mocked relay round-trip per launch (default 40)")
    ap.add_argument("--seq-heights", type=int, default=48,
                    help="heights for the per-height baseline (default 48)")
    ap.add_argument("--commits", type=int, default=100,
                    help="consensus commit batches per column (default 100)")
    ap.add_argument("--commit-sigs", type=int, default=128,
                    help="signatures per commit batch (default 128)")
    ap.add_argument("--flood-heights", type=int, default=20_000,
                    help="chain prebuilt for the flood column (default 20000)")
    ap.add_argument("--real", action="store_true",
                    help="run live kernels instead of the mocked relay")
    ap.add_argument("--out", default="",
                    help="also write the artifact JSON to this path")
    args = ap.parse_args(argv)

    from tendermint_tpu.libs import jaxcache

    import jax

    jaxcache.enable(jax, os.path.dirname(os.path.abspath(__file__)))

    from tendermint_tpu.blocksync.replay import ReplayEngine
    from tendermint_tpu.ops import epoch_cache as _epoch
    from tendermint_tpu.ops import pipeline as _pl
    from tendermint_tpu.ops._testing import mock_mempool_prepare
    from tendermint_tpu.ops.entry_block import EntryBlock
    from tendermint_tpu.types import validation as _val
    from tendermint_tpu.types.block import BlockID
    from tendermint_tpu.types.part_set import BLOCK_PART_SIZE_BYTES, PartSet

    chain_id = "blocksync-bench"
    print(f"# {args.val_sets} validator sets x {args.vals} vals, "
          f"rotation every {args.rotate} heights", file=sys.stderr)
    vsets = _replay_bench_valsets(args.vals, args.val_sets)

    def vals_at(h):
        return vsets[((h - 1) // args.rotate) % len(vsets)][0]

    class _St:
        def __init__(self, cid):
            self.chain_id = cid
            self.validators = vals_at(1)
            self.last_block_height = 0

    def _noop_save(block, parts, seen_commit):
        pass

    def _mk_apply(st):
        def apply(bid, block):
            st.last_block_height = block.header.height
            st.validators = vals_at(block.header.height + 1)
            return st

        return apply

    # the consensus lane's payload: one commit-shaped batch resubmitted
    # per "height" at PRIORITY_CONSENSUS (fake keys — mocked relay)
    crng = random.Random(0xC0117)
    commit_block = EntryBlock.from_entries([
        (crng.randbytes(32), b"bench-commit-%d" % i, crng.randbytes(64))
        for i in range(args.commit_sigs)
    ])

    _epoch.reset(8)
    launches = [0]
    real_prepare = _pl.AsyncBatchVerifier._prepare
    if not args.real:
        _mock = mock_mempool_prepare(real_prepare, args.rtt_ms / 1e3)

        def _counting_prepare(entries):
            f, pargs, rlc, bucket = _mock(entries)

            def launch(*xs):
                launches[0] += 1
                return f(*xs)

            return launch, pargs, rlc, bucket

        _pl.AsyncBatchVerifier._prepare = staticmethod(_counting_prepare)
    # force-device discipline: the per-height baseline (22-sig batches)
    # and the commit column must pay the relay cost model, not quietly
    # route to host crypto (where the fake signatures would also fail)
    os.environ["TM_TPU_FORCE_DEVICE"] = "1"
    _swi = sys.getswitchinterval()
    sys.setswitchinterval(0.001)
    v = _pl.AsyncBatchVerifier(depth=3)
    eng = ReplayEngine(window=args.window, synchronous=True, verifier=v)

    def commit_column(n):
        lats = []
        for _ in range(n):
            t0 = time.perf_counter()
            v.submit(
                commit_block, priority=_pl.PRIORITY_CONSENSUS
            ).result(timeout=300)
            lats.append(time.perf_counter() - t0)
        return lats

    try:
        # -- column A: the headline — windowed chain replay --------------
        print(f"# replaying {args.heights} heights "
              f"(window {args.window})", file=sys.stderr)
        gen = _replay_bench_chain(
            chain_id, vsets, args.rotate, random.Random(0xB10C)
        )
        st = _St(chain_id)
        apply = _mk_apply(st)
        buf = []
        t_replay = t_build = 0.0
        applied = 0
        launches[0] = 0
        while applied < args.heights:
            t0 = time.perf_counter()
            while len(buf) < args.window + 1:
                buf.append(next(gen))
            t_build += time.perf_counter() - t0
            t0 = time.perf_counter()
            st, out_r = eng.replay_blocks(st, buf, _noop_save, apply)
            t_replay += time.perf_counter() - t0
            if out_r.applied <= 0:
                raise RuntimeError(
                    f"replay stalled at height {st.last_block_height}: "
                    f"{out_r.error!r}"
                )
            applied += out_r.applied
            del buf[: out_r.applied]
        rate = applied / t_replay
        n_launches = launches[0]
        stats = eng.stats()
        kernel_rate = (
            applied / (n_launches * (args.rtt_ms / 1e3))
            if (n_launches and not args.real) else None
        )
        print(f"# {applied} heights in {t_replay:.1f}s replay "
              f"(+{t_build:.1f}s synthesis, off the clock), "
              f"{n_launches} launches", file=sys.stderr)

        import gc

        gc.collect()
        gc.freeze()

        # -- column B: consensus commits, unloaded -----------------------
        p99_unloaded = _p99_ms(commit_column(args.commits))

        # -- column C: the same commit cadence while a node catches up ---
        # the flood chain is prebuilt so the driver thread's only work
        # is feeding the engine (synthesis must not throttle the flood)
        fgen = _replay_bench_chain(
            chain_id, vsets, args.rotate, random.Random(0xF100D)
        )
        fchain = [next(fgen) for _ in range(args.flood_heights + 1)]
        stop = threading.Event()
        flood_applied = [0]

        def flood():
            feng = ReplayEngine(
                window=args.window, synchronous=True, verifier=v
            )
            fst = _St(chain_id)
            fapply = _mk_apply(fst)
            pos = 0
            while not stop.is_set():
                if pos + 1 >= len(fchain):
                    pos = 0
                    fst = _St(chain_id)
                run = fchain[pos : pos + args.window + 1]
                fst, fo = feng.replay_blocks(
                    fst, run, _noop_save, fapply,
                    should_stop=stop.is_set,
                )
                if fo.applied <= 0:
                    break
                pos += fo.applied
                flood_applied[0] += fo.applied

        ft = threading.Thread(target=flood, daemon=True)
        ft.start()
        time.sleep(args.rtt_ms / 1e3 * 4)  # let replay chunks queue
        p99_flood = _p99_ms(commit_column(args.commits))
        stop.set()
        ft.join(timeout=60)

        # -- baseline: one launch per height on the SAME mocked engine ---
        seq_n = min(args.seq_heights, args.rotate - 1)
        sgen = _replay_bench_chain(
            chain_id, vsets, args.rotate, random.Random(0x5E0)
        )
        schain = [next(sgen) for _ in range(seq_n + 1)]
        t0 = time.perf_counter()
        for i in range(seq_n):
            b = schain[i]
            h = b.header.height
            parts = PartSet.from_data(b.encode(), BLOCK_PART_SIZE_BYTES)
            bid = BlockID(hash=b.hash(), part_set_header=parts.header())
            prepared, _synced = _val.prepare_commit_range(
                chain_id, vals_at(h),
                [(h, bid, schain[i + 1].last_commit)],
            )
            _h, eb, conclude = prepared[0]
            valid = v.submit(
                eb, priority=_pl.PRIORITY_REPLAY
            ).result(timeout=300)
            conclude(valid[: len(eb)])
        seq_rate = seq_n / (time.perf_counter() - t0)
    finally:
        eng.close()
        v.close()
        sys.setswitchinterval(_swi)
        os.environ.pop("TM_TPU_FORCE_DEVICE", None)
        _pl.AsyncBatchVerifier._prepare = real_prepare
        import gc

        gc.unfreeze()

    out = {
        "schema_version": 1,
        "metric": "blocksync_replay_heights_per_s",
        "value": round(rate, 1),
        "unit": "heights/s",
        "mode": "real" if args.real else "mocked-relay",
        "backend": os.environ.get("JAX_PLATFORMS", "") or "cpu",
        "heights": applied,
        "vals": args.vals,
        "val_sets": args.val_sets,
        "rotate": args.rotate,
        "window": args.window,
        "relay_rtt_ms": args.rtt_ms if not args.real else None,
        "launches": n_launches,
        "sigs_submitted": stats["sigs_submitted"],
        "range_hit_rate": round(stats["hit_rate"], 4),
        "fallback_ranges": stats["fallback_ranges"],
        "kernel_serial_heights_per_s": (
            round(kernel_rate, 1) if kernel_rate else None
        ),
        "vs_kernel_serial": (
            round(rate / kernel_rate, 2) if kernel_rate else None
        ),
        "replay_seq_heights_per_s": round(seq_rate, 1),
        "vs_sequential": round(rate / seq_rate, 2) if seq_rate else None,
        "chain_synth_heights_per_s": (
            round(applied / t_build, 1) if t_build else None
        ),
        "commit_p99_unloaded_ms": round(p99_unloaded, 2),
        "commit_p99_flood_ms": round(p99_flood, 2),
        "flood_latency_ratio": (
            round(p99_flood / p99_unloaded, 2) if p99_unloaded else None
        ),
        "flood_heights_applied": flood_applied[0],
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1)
            fh.write("\n")


def votes_main(argv) -> None:
    """`bench.py votes` — device-batched live-vote ingress (ISSUE 15).

    Floods gossiped prevotes through the FULL AddVote split path (host
    check_vote, vote-ingress windowing, EntryBlock packing, verdict
    application into real VoteSets) with the device mocked behind a
    fixed per-launch relay RTT (mock_vote_prepare — real windowing,
    packing, host prep and transfer; the launch's verdict matures
    rtt_ms after launch). Headline: vote signature verdicts/s through
    the windowed accumulator, measured to the LAST verdict applied.
    The honest baseline is the SAME mocked engine driven per-vote
    (window=0, batch=1 — one relay launch per vote, the shape AddVote
    had before the accumulator), under the TM_TPU_FORCE_DEVICE
    discipline so neither column quietly routes to host crypto.

    Prints ONE JSON line; --out also writes it as an artifact file
    (VOTES_r*.json, schema_version 1, rendered by tools/bench_report.py
    --trajectory and gated by --compare)."""
    import argparse
    import threading

    ap = argparse.ArgumentParser(prog="bench.py votes")
    ap.add_argument("--votes", type=int, default=4096,
                    help="signed votes in the flood (default 4096)")
    ap.add_argument("--vals", type=int, default=64,
                    help="validators in the set (default 64)")
    ap.add_argument("--batch", type=int, default=256,
                    help="accumulator max batch (default 256)")
    ap.add_argument("--window-ms", type=float, default=2.0,
                    help="accumulator window (default 2)")
    ap.add_argument("--rtt-ms", type=float, default=40.0,
                    help="mocked relay round-trip per launch (default 40)")
    ap.add_argument("--seq-votes", type=int, default=48,
                    help="votes for the per-vote baseline (default 48)")
    ap.add_argument("--real", action="store_true",
                    help="run live kernels instead of the mocked relay")
    ap.add_argument("--out", default="",
                    help="also write the artifact JSON to this path")
    args = ap.parse_args(argv)

    from tendermint_tpu.libs import jaxcache

    import jax

    jaxcache.enable(jax, os.path.dirname(os.path.abspath(__file__)))

    from tendermint_tpu.consensus import vote_ingress as _vi
    from tendermint_tpu.crypto import ed25519 as _ed
    from tendermint_tpu.ops import epoch_cache as _epoch
    from tendermint_tpu.ops import pipeline as _pl
    from tendermint_tpu.ops._testing import mock_vote_prepare
    from tendermint_tpu.types import (
        BlockID,
        PartSetHeader,
        Timestamp,
        Validator,
        ValidatorSet,
        Vote,
        VoteSet,
    )
    from tendermint_tpu.types.vote import PREVOTE_TYPE

    chain_id = "votes-bench"
    height = 10
    n_rounds = -(-args.votes // args.vals)
    n_votes = n_rounds * args.vals
    print(f"# signing {n_votes} votes ({args.vals} vals x {n_rounds} "
          "rounds)", file=sys.stderr)
    pairs = []
    for i in range(args.vals):
        sk = _ed.gen_priv_key(bytes([(i % 255) + 1]) * 31 +
                              bytes([i // 255 + 1]))
        pairs.append((sk, Validator.new(sk.pub_key(), 100)))
    vset = ValidatorSet.new([v for _, v in pairs])
    by_addr = {v.address: sk for sk, v in pairs}
    sks = [by_addr[v.address] for v in vset.validators]
    bid = BlockID(hash=b"\x07" * 32,
                  part_set_header=PartSetHeader(total=1, hash=b"\x07" * 32))
    votes = []
    for r in range(n_rounds):
        for i, sk in enumerate(sks):
            vote = Vote(
                type=PREVOTE_TYPE, height=height, round=r, block_id=bid,
                timestamp=Timestamp(seconds=1_600_000_000, nanos=0),
                validator_address=vset.validators[i].address,
                validator_index=i,
            )
            msg = vote.sign_bytes(chain_id)
            votes.append((
                Vote(**{**vote.__dict__, "signature": sk.sign(msg)}), msg,
            ))

    def fresh_sets():
        return {r: VoteSet(chain_id, height, r, PREVOTE_TYPE, vset)
                for r in range(n_rounds)}

    _epoch.reset(8)
    _epoch.note_valset(vset)  # register
    _epoch.note_valset(vset)  # warm: windows attach val_idx + epoch_key
    real_prepare = _pl.AsyncBatchVerifier._prepare
    if not args.real:
        _pl.AsyncBatchVerifier._prepare = staticmethod(
            mock_vote_prepare(real_prepare, args.rtt_ms / 1e3)
        )
    # both columns under the force-device discipline (see mempool_main)
    os.environ["TM_TPU_FORCE_DEVICE"] = "1"
    _swi = sys.getswitchinterval()
    sys.setswitchinterval(0.001)
    v = _pl.AsyncBatchVerifier(depth=3)

    def make_apply(sets, counter, done):
        def apply(batch, verdicts, error):
            for i, p in enumerate(batch):
                if error is None and verdicts[i]:
                    try:
                        sets[p.vote.round].apply_vote_verdict(p.vote, True)
                    except Exception:  # noqa: BLE001 — tally only
                        pass
                counter[0] += 1
            if counter[0] >= counter[1]:
                done.set()
        return apply

    try:
        # -- column A: the headline — windowed flood ---------------------
        sets = fresh_sets()
        done = threading.Event()
        counter = [0, n_votes]
        acc = _vi.VoteIngress(make_apply(sets, counter, done), verifier=v,
                              max_batch=args.batch,
                              window_ms=args.window_ms)
        try:
            t0 = time.perf_counter()
            for vote, msg in votes:
                chk = sets[vote.round].check_vote(vote)  # host stage
                assert chk is not None
                acc.submit(_vi.PendingVote(
                    vote, "bench-peer", chk.pub_key.bytes(), msg,
                    t_enq=time.perf_counter(),
                ), vset)
            acc.flush_now()
            if not done.wait(timeout=600):
                raise RuntimeError(
                    f"only {counter[0]}/{n_votes} verdicts arrived"
                )
            dt = time.perf_counter() - t0
            rate = n_votes / dt
            stats = acc.stats()
            n_applied = sum(
                1 for r in range(n_rounds) for i in range(args.vals)
                if sets[r].bit_array().get_index(i)
            )
            if n_applied != n_votes:
                print(f"# WARNING: {n_votes - n_applied} votes not "
                      "applied", file=sys.stderr)
        finally:
            acc.close()

        # -- baseline: per-vote dispatch on the SAME mocked engine -------
        seq_sets = fresh_sets()
        seq_n = min(args.seq_votes, n_votes)
        seq_done = threading.Event()
        seq_counter = [0, seq_n]
        seq_acc = _vi.VoteIngress(
            make_apply(seq_sets, seq_counter, seq_done), verifier=v,
            max_batch=1, window_ms=0.0,
        )
        try:
            t0 = time.perf_counter()
            for vote, msg in votes[:seq_n]:
                chk = seq_sets[vote.round].check_vote(vote)
                want = seq_counter[0] + 1
                seq_acc.submit(_vi.PendingVote(
                    vote, "bench-peer", chk.pub_key.bytes(), msg,
                    t_enq=time.perf_counter(),
                ), vset)
                seq_acc.flush_now()
                # sequential shape: wait for THIS vote's verdict before
                # the next — one relay launch per vote
                deadline = time.perf_counter() + 300
                while (seq_counter[0] < want
                       and time.perf_counter() < deadline):
                    time.sleep(0.0005)
            seq_rate = seq_n / (time.perf_counter() - t0)
        finally:
            seq_acc.close()
    finally:
        v.close()
        sys.setswitchinterval(_swi)
        os.environ.pop("TM_TPU_FORCE_DEVICE", None)
        _pl.AsyncBatchVerifier._prepare = real_prepare

    out = {
        "schema_version": 1,
        "metric": "vote_ingress_votes_per_s",
        "value": round(rate, 1),
        "unit": "votes/s",
        "mode": "real" if args.real else "mocked-relay",
        "backend": os.environ.get("JAX_PLATFORMS", "") or "cpu",
        "votes": n_votes,
        "vals": args.vals,
        "rounds": n_rounds,
        "ingress_batch": args.batch,
        "ingress_window_ms": args.window_ms,
        "relay_rtt_ms": args.rtt_ms if not args.real else None,
        "votes_seq_votes_per_s": round(seq_rate, 1),
        "vs_sequential": round(rate / seq_rate, 2) if seq_rate else None,
        "ingress_windows": stats["batches"],
        "ingress_batch_wait_ms_avg": round(stats["batch_wait_ms_avg"], 2),
        "window_dups": stats["window_dups"],
        "memo_hits": stats["memo_hits"],
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1)
            fh.write("\n")


def schemes_main(argv) -> None:
    """`bench.py schemes` — the secp256k1 scheme lane at committee scale
    (ISSUE 19).

    Verifies a 10k-validator all-secp256k1 commit through the FULL
    production seam (prepare_commit_light -> scheme-routed pipeline
    prep -> launch -> conclude) with the device mocked behind a fixed
    per-launch relay RTT (mock_vote_prepare: the real host prep — epoch
    table gather, GLV decomposition, scalar packing — and the H2D
    transfer run unchanged; the launch's verdict matures rtt_ms after
    launch). Headline: counted commit signatures/s to conclude().

    The honest baseline is the SAME mocked engine driven per-signature
    (one relay launch per signature — the shape the reference's
    "secp256k1 never batches" verdict forces, crypto/batch/batch.go:
    26-33), so the ratio measures exactly what the scheme lane adds:
    signatures fused per relay command. Gated at >= 10x (the ISSUE 19
    acceptance); kernel-verdict correctness is pinned separately by
    tests/test_secp_lane.py and `tools/prep_bench.py --schemes`, which
    run the kernel for real.

    Prints ONE JSON line; --out also writes it as an artifact file
    (SCHEMES_r*.json, schema_version 1, rendered by tools/bench_report.py
    --trajectory and gated by --compare)."""
    import argparse

    import numpy as np

    ap = argparse.ArgumentParser(prog="bench.py schemes")
    ap.add_argument("--vals", type=int, default=10240,
                    help="secp256k1 validators in the set (default 10240)")
    ap.add_argument("--rtt-ms", type=float, default=40.0,
                    help="mocked relay round-trip per launch (default 40)")
    ap.add_argument("--seq-sigs", type=int, default=48,
                    help="signatures for the per-sig baseline (default 48)")
    ap.add_argument("--real", action="store_true",
                    help="run live kernels instead of the mocked relay")
    ap.add_argument("--out", default="",
                    help="also write the artifact JSON to this path")
    args = ap.parse_args(argv)

    from tendermint_tpu.libs import jaxcache

    import jax

    jaxcache.enable(jax, os.path.dirname(os.path.abspath(__file__)))

    from tendermint_tpu.crypto import secp256k1 as _secp
    from tendermint_tpu.ops import epoch_cache as _epoch
    from tendermint_tpu.ops import pipeline as _pl
    from tendermint_tpu.ops._testing import mock_vote_prepare
    from tendermint_tpu.ops.entry_block import EntryBlock
    from tendermint_tpu.types import validation as V
    from tendermint_tpu.types.block import (
        BLOCK_ID_FLAG_COMMIT,
        BlockID,
        Commit,
        CommitSig,
        PartSetHeader,
    )
    from tendermint_tpu.types.validator_set import Validator, ValidatorSet
    from tendermint_tpu.wire.canonical import Timestamp

    chain_id = "schemes-bench"
    n_ord = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
    rng = np.random.RandomState(191)
    print(f"# deriving {args.vals} secp256k1 validators", file=sys.stderr)
    vals, sigs = [], []
    for i in range(args.vals):
        pk = _secp.PrivKey((i + 1).to_bytes(32, "big")).pub_key()
        vals.append(Validator.new(pk, 100))
        # full-range lower-S (r, s): signing 10k purepy ECDSA sigs costs
        # ~11 ms each and the mocked relay never checks validity, but
        # the rows must still pay the FULL host prep (range checks pass,
        # GLV decomposition runs) — same rationale as
        # build_synthetic_commit's random ed25519 signatures
        r = int.from_bytes(rng.bytes(32), "big") % (n_ord - 1) + 1
        s = int.from_bytes(rng.bytes(32), "big") % (n_ord // 2) + 1
        sigs.append(CommitSig(
            block_id_flag=BLOCK_ID_FLAG_COMMIT,
            validator_address=pk.address(),
            timestamp=Timestamp(seconds=1_700_000_000, nanos=int(i) + 1),
            signature=r.to_bytes(32, "big") + s.to_bytes(32, "big"),
        ))
    # keep commit.signatures index-aligned with the validator list
    vset = ValidatorSet(validators=vals, proposer=vals[0])
    bid = BlockID(hash=b"\x13" * 32,
                  part_set_header=PartSetHeader(total=1, hash=b"\x13" * 32))
    commit = Commit(height=19, round=0, block_id=bid, signatures=sigs)

    _epoch.reset(8)
    _epoch.note_valset(vset)  # register
    _epoch.note_valset(vset)  # warm: blocks attach val_idx + epoch_key
    real_prepare = _pl.AsyncBatchVerifier._prepare
    launches = [0]
    if not args.real:
        mocked = mock_vote_prepare(real_prepare, args.rtt_ms / 1e3)

        def counting(entries):
            launches[0] += 1
            return mocked(entries)

        _pl.AsyncBatchVerifier._prepare = staticmethod(counting)
    os.environ["TM_TPU_FORCE_DEVICE"] = "1"
    v = _pl.AsyncBatchVerifier(depth=3)
    try:
        def run_once():
            entries, conclude = V.prepare_commit_light(
                chain_id, vset, bid, commit.height, commit
            )
            verdicts = np.asarray(v.submit(entries).result(timeout=600))
            conclude(verdicts)
            return len(entries)

        # warm rep: epoch Q-table decompression + shape warmup happen
        # once per process, outside the timed window
        run_once()
        launches[0] = 0
        t0 = time.perf_counter()
        n_counted = run_once()
        dt = time.perf_counter() - t0
        rate = n_counted / dt
        headline_launches = launches[0]

        # -- baseline: per-signature dispatch on the SAME mocked engine -
        seq_n = min(args.seq_sigs, args.vals)
        rows = [
            (vset.validators[i].pub_key.bytes(), b"seq-%d" % i,
             sigs[i].signature)
            for i in range(seq_n)
        ]
        t0 = time.perf_counter()
        for row in rows:
            blk = EntryBlock.from_entries([row], scheme="secp256k1")
            # sequential shape: wait for THIS signature's verdict before
            # the next — one relay launch per signature
            np.asarray(v.submit(blk).result(timeout=300))
        seq_rate = seq_n / (time.perf_counter() - t0)
    finally:
        v.close()
        os.environ.pop("TM_TPU_FORCE_DEVICE", None)
        _pl.AsyncBatchVerifier._prepare = real_prepare

    speedup = rate / seq_rate if seq_rate else None
    out = {
        "schema_version": 1,
        "metric": "secp_commit_sigs_per_s",
        "value": round(rate, 1),
        "unit": "sigs/s",
        "mode": "real" if args.real else "mocked-relay",
        "backend": os.environ.get("JAX_PLATFORMS", "") or "cpu",
        "scheme": "secp256k1",
        "vals": args.vals,
        "sigs_counted": n_counted,
        "relay_rtt_ms": args.rtt_ms if not args.real else None,
        "launches": headline_launches,
        "epoch": "warm",
        "secp_seq_sigs_per_s": round(seq_rate, 1),
        "vs_per_sig": round(speedup, 2) if speedup else None,
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1)
            fh.write("\n")
    if speedup is None or speedup < 10.0:
        print(f"# FAIL: scheme-lane speedup {speedup} < 10x the per-sig "
              "baseline (ISSUE 19 acceptance)", file=sys.stderr)
        sys.exit(1)


def bls_main(argv) -> None:
    """`bench.py bls` — the BLS12-381 aggregation lane at committee
    scale (ISSUE 20).

    Drives K aggregated commits (ONE 96-byte signature + a signer
    bitmap each, 2302.00418's BLS shape) through the FULL production
    seam (prepare_aggregated_commit -> AggBlock -> pipeline coalescer
    -> fused multi-pairing launch -> conclude) with the device mocked
    behind a fixed per-launch relay RTT (mock_vote_prepare: the real
    host prep — signature/pubkey status walk, epoch G1-table columns,
    mask/RLC-coefficient packing — and the H2D transfer run unchanged;
    the launch's verdict matures rtt_ms after launch). Headline:
    aggregated commits/s to conclude().

    Two economics columns ride along, both ANALYTIC from the launch
    ledger (widths the coalescer actually dispatched), not timed:

      pairings_per_commit   a sequential BLS verify pays 2 pairings
                            (2 Miller loops + 2 final exponentiations)
                            per commit; the fused lane pays 2W Miller
                            loops + ONE shared final exp per W-wide
                            launch — counting a pairing as one Miller +
                            one final exp, that amortizes to
                            1 + 1/(2W) < 2. This RLC fusion is the
                            structural contrast with the ECDSA lane,
                            where no such cross-signature fusion exists.
      wire_ratio_vs_ed25519 bytes of the aggregated commit vs the SAME
                            committee's per-signature ed25519 commit
                            (96B sig + V/8 bitmap vs V 64-byte rows +
                            addresses + timestamps) — gated at <= 0.10
                            for the 128-validator acceptance committee.

    Exits nonzero when a gate fails. Prints ONE JSON line; --out also
    writes it as an artifact file (AGG_r*.json, schema_version 1,
    rendered by tools/bench_report.py --trajectory and gated by
    --compare)."""
    import argparse

    import numpy as np

    ap = argparse.ArgumentParser(prog="bench.py bls")
    ap.add_argument("--vals", type=int, default=128,
                    help="BLS validators in the committee (default 128)")
    ap.add_argument("--commits", type=int, default=16,
                    help="aggregated commits in the window (default 16)")
    ap.add_argument("--rtt-ms", type=float, default=40.0,
                    help="mocked relay round-trip per launch (default 40)")
    ap.add_argument("--out", default="",
                    help="also write the artifact JSON to this path")
    args = ap.parse_args(argv)

    from tendermint_tpu.libs import jaxcache

    import jax

    jaxcache.enable(jax, os.path.dirname(os.path.abspath(__file__)))

    from tendermint_tpu.crypto import bls12381 as _bls
    from tendermint_tpu.libs.bits import BitArray
    from tendermint_tpu.ops import epoch_cache as _epoch
    from tendermint_tpu.ops import pipeline as _pl
    from tendermint_tpu.ops._testing import mock_vote_prepare
    from tendermint_tpu.types import validation as V
    from tendermint_tpu.types.block import (
        BLOCK_ID_FLAG_COMMIT,
        AggregatedCommit,
        BlockID,
        Commit,
        CommitSig,
        PartSetHeader,
    )
    from tendermint_tpu.types.validator_set import Validator, ValidatorSet
    from tendermint_tpu.wire.canonical import Timestamp

    chain_id = "bls-bench"
    print(f"# deriving {args.vals} bls12381 validators (pure-python G1 "
          "scalar muls)", file=sys.stderr)
    vals = []
    for i in range(args.vals):
        pk = _bls.PrivKey((i + 1).to_bytes(32, "big")).pub_key()
        vals.append(Validator.new(pk, 100))
    vset = ValidatorSet(validators=vals, proposer=vals[0])
    bid = BlockID(hash=b"\x20" * 32,
                  part_set_header=PartSetHeader(total=1, hash=b"\x20" * 32))

    # ONE real signature shared across the window: the mocked relay
    # never runs the pairing, but the host prep's signature_status
    # (decompress + G2 subgroup check) must see a live aggregate — and
    # memoizes per sig bytes exactly like production's repeated gossip
    print("# signing one aggregate (hash-to-G2 + cofactor clearing)",
          file=sys.stderr)
    full = BitArray(args.vals)
    for i in range(args.vals):
        full.set_index(i, True)
    probe = AggregatedCommit(height=1, round=0, block_id=bid, signers=full)
    sig = _bls.PrivKey(b"\x2a" * 32).sign(probe.sign_bytes(chain_id))

    def agg_at(h):
        ba = BitArray(args.vals)
        for i in range(args.vals):
            ba.set_index(i, True)
        return AggregatedCommit(height=h, round=0, block_id=bid,
                                signature=sig, signers=ba)

    # -- wire economics (real encodings, independent of the relay) ------
    agg_bytes = len(agg_at(1).encode())
    ed_sigs = [CommitSig(
        block_id_flag=BLOCK_ID_FLAG_COMMIT,
        validator_address=v.address,
        timestamp=Timestamp(seconds=1_700_000_000, nanos=i + 1),
        signature=bytes(64),
    ) for i, v in enumerate(vals)]
    ed_bytes = len(Commit(height=1, round=0, block_id=bid,
                          signatures=ed_sigs).encode())
    wire_ratio = agg_bytes / ed_bytes

    _epoch.reset(8)
    _epoch.note_valset(vset)  # register
    _epoch.note_valset(vset)  # warm: pub48 columns + device G1 tables
    real_prepare = _pl.AsyncBatchVerifier._prepare
    widths = []
    mocked = mock_vote_prepare(real_prepare, args.rtt_ms / 1e3)

    def counting(entries):
        widths.append(len(entries))
        return mocked(entries)

    _pl.AsyncBatchVerifier._prepare = staticmethod(counting)
    v = _pl.AsyncBatchVerifier(depth=3)
    try:
        def run_once():
            pairs = [V.prepare_aggregated_commit(
                chain_id, vset, bid, h, agg_at(h), k_hint=args.commits)
                for h in range(1, args.commits + 1)]
            futs = [(v.submit(blk), conc) for blk, conc in pairs]
            for fut, conc in futs:
                conc(np.asarray(fut.result(timeout=600)))
            return len(pairs)

        # warm rep: pubkey_status memoization + epoch table upload +
        # shape warmup happen once per process, outside the timed window
        run_once()
        widths.clear()
        t0 = time.perf_counter()
        k = run_once()
        dt = time.perf_counter() - t0
    finally:
        v.close()
        _pl.AsyncBatchVerifier._prepare = real_prepare

    launches = len(widths)
    # a pairing = one Miller loop + one final exponentiation; a W-wide
    # fused launch runs 2W Millers (pads included — they burn device
    # lanes like any fixed-shape batch) and ONE shared final exp
    millers = sum(2 * w for w in widths)
    final_exps = launches
    pairings = millers / 2 + final_exps / 2
    pairings_per_commit = pairings / k
    sigs_replaced_per_pairing = (args.vals * k) / pairings
    rate = k / dt

    out = {
        "schema_version": 1,
        "metric": "bls_agg_commits_per_s",
        "value": round(rate, 1),
        "unit": "commits/s",
        "mode": "mocked-relay",
        "backend": os.environ.get("JAX_PLATFORMS", "") or "cpu",
        "scheme": "bls12381",
        "vals": args.vals,
        "commits": k,
        "relay_rtt_ms": args.rtt_ms,
        "launches": launches,
        "launch_widths": widths,
        "epoch": "warm",
        "pairings_per_commit": round(pairings_per_commit, 4),
        "sigs_replaced_per_pairing": round(sigs_replaced_per_pairing, 1),
        "agg_wire_bytes": agg_bytes,
        "ed25519_wire_bytes": ed_bytes,
        "wire_ratio_vs_ed25519": round(wire_ratio, 4),
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1)
            fh.write("\n")
    fails = []
    if pairings_per_commit >= 2.0:
        fails.append(f"pairings_per_commit {pairings_per_commit:.3f} >= 2 "
                     "(fusion must amortize the final exponentiation)")
    if args.vals >= 128 and wire_ratio > 0.10:
        fails.append(f"wire ratio {wire_ratio:.4f} > 0.10 vs the "
                     "per-signature ed25519 commit")
    for f in fails:
        print(f"# FAIL: {f} (ISSUE 20 acceptance)", file=sys.stderr)
    if fails:
        sys.exit(1)


def lanes_main(argv) -> None:
    """`bench.py lanes` — the ingress-fabric latency-vs-load curve
    (ISSUE 17).

    Drives one fabric lane per WINDOW POLICY through the mocked relay
    (mock_mempool_prepare: real windowing, EntryBlock packing, host prep
    and transfer; each launch's verdict matures rtt_ms after launch) at
    both ends of the load curve:

      idle   lone signatures at a fixed inter-arrival — the latency a
             single request pays when nothing else is queued (p99 ms)
      flood  a paced signature flood — sustained sigs/s measured to the
             LAST verdict delivered

    Three policies: fixed-shallow (the latency end point: small window,
    small batch), fixed-deep (the throughput end point: big window, big
    batch), and adaptive (base == shallow, growth cap beyond deep).
    The gate is that adaptive holds BOTH ends of the curve:

      * at idle it strictly beats deep on p99 latency and stays within
        tolerance of shallow;
      * at flood it strictly beats shallow on RELAY-COMMAND ECONOMICS —
        sigs per launch window, the quantity the window policy actually
        controls (the relay is one serial command channel, so fewer,
        fuller launches is the 2302.00418 batch-economics win) — while
        holding wall-clock throughput within tolerance of BOTH fixed
        policies. (Raw sigs/s alone cannot separate shallow from deep
        under backlog: flushes take the whole queue, so a backlogged
        shallow lane self-heals into big launches. The launch count is
        the honest fingerprint of the policy.)

    Exits nonzero when adaptive loses the curve.

    Prints ONE JSON line; --out also writes it as an artifact file
    (LANES_r*.json, schema_version 1, rendered by tools/bench_report.py
    --trajectory and gated by --compare)."""
    import argparse
    import threading

    ap = argparse.ArgumentParser(prog="bench.py lanes")
    ap.add_argument("--flood-sigs", type=int, default=8192,
                    help="signatures in the flood (default 8192)")
    ap.add_argument("--idle-sigs", type=int, default=25,
                    help="lone signatures at the idle end (default 25)")
    ap.add_argument("--idle-gap-ms", type=float, default=40.0,
                    help="idle inter-arrival (default 40)")
    ap.add_argument("--burst", type=int, default=96,
                    help="flood pacing: sigs per 1 ms burst (default 96)")
    ap.add_argument("--rtt-ms", type=float, default=20.0,
                    help="mocked relay round-trip per launch (default 20)")
    ap.add_argument("--out", default="",
                    help="also write the artifact JSON to this path")
    args = ap.parse_args(argv)

    from tendermint_tpu.libs import jaxcache

    import jax

    jaxcache.enable(jax, os.path.dirname(os.path.abspath(__file__)))

    from tendermint_tpu.crypto import ed25519 as _ed
    from tendermint_tpu.ops import ingress as _fabric
    from tendermint_tpu.ops import pipeline as _pl
    from tendermint_tpu.ops._testing import drain_pool, mock_mempool_prepare

    # 8 real signed triples, repeated to fill the streams: the relay is
    # mocked (all-accept), so prep cost per entry — what the policies
    # differ on — is what matters, not verdict content
    triples = []
    for i in range(8):
        sk = _ed.gen_priv_key(bytes([i + 1]) * 32)
        msg = b"lanes-bench-%d" % i
        triples.append((sk.pub_key().bytes(), msg, sk.sign(msg)))

    # the three window policies: shallow/deep are the fixed end points,
    # adaptive spans past both (batch cap 8x base, window x8 / /4)
    policies = {
        "shallow": dict(batch=32, window_ms=4.0, adaptive=False),
        "deep": dict(batch=256, window_ms=32.0, adaptive=False),
        "adaptive": dict(batch=64, window_ms=4.0, adaptive=True),
    }

    real_prepare = _pl.AsyncBatchVerifier._prepare
    _pl.AsyncBatchVerifier._prepare = staticmethod(
        mock_mempool_prepare(real_prepare, args.rtt_ms / 1e3)
    )
    os.environ["TM_TPU_FORCE_DEVICE"] = "1"
    eng = _fabric.IngressEngine()
    results = {}
    leaked = 0
    try:
        for name, pol in policies.items():
            # a FRESH verifier per policy — a shared one lets the
            # previous policy's flood tail queue under the next one's
            # idle measurement. depth=1: the relay is ONE serial command
            # channel (PERF_r05 §2), so sigs per relay command — what
            # the window policy controls — bounds flood throughput
            # exactly the way the 2302.00418 batch economics say
            v = _pl.AsyncBatchVerifier(depth=1)
            mtx = threading.Lock()
            lat: list = []
            count = [0]
            target = [0]
            done = threading.Event()

            def deliver(items, verdicts, err, lat=lat, count=count,
                        target=target, done=done, mtx=mtx):
                now = time.perf_counter()
                with mtx:
                    for it in items:
                        lat.append((now - it.t_enq) * 1e3)
                    count[0] += len(items)
                    if count[0] >= target[0]:
                        done.set()

            lane = eng.register(_fabric.LaneSpec(
                name=f"bench-{name}", priority=_fabric.PRIORITY_INGRESS,
                verifier=v, entries_fn=lambda i: triples[i % 8],
                deliver=deliver, **pol))
            try:
                # -- idle end: lone signatures, per-item latency ---------
                with mtx:
                    lat.clear()
                    count[0] = 0
                    target[0] = args.idle_sigs
                    done.clear()
                for i in range(args.idle_sigs):
                    lane.submit(i)
                    time.sleep(args.idle_gap_ms / 1e3)
                if not done.wait(timeout=120):
                    raise RuntimeError(f"{name}: idle verdicts missing")
                with mtx:
                    idle_lat = sorted(lat)
                idle_p99 = idle_lat[int(0.99 * (len(idle_lat) - 1))]

                # -- flood end: paced bursts, time to last verdict -------
                with mtx:
                    lat.clear()
                    count[0] = 0
                    target[0] = args.flood_sigs
                    done.clear()
                t0 = time.perf_counter()
                for base in range(0, args.flood_sigs, args.burst):
                    for i in range(base,
                                   min(base + args.burst, args.flood_sigs)):
                        lane.submit(i)
                    time.sleep(0.001)
                if not done.wait(timeout=300):
                    raise RuntimeError(f"{name}: flood verdicts missing")
                flood_dt = time.perf_counter() - t0
                st = lane.stats()
            finally:
                lane.close(timeout=30)
                drain_pool(v._pool)
                leaked += v._pool.stats()["in_flight"]
                v.close()
            results[name] = {
                "idle_p99_ms": round(idle_p99, 2),
                "flood_sigs_per_s": round(args.flood_sigs / flood_dt, 1),
                "flood_launch_windows": st["batches"],
                "flood_sigs_per_window": round(
                    args.flood_sigs / max(st["batches"], 1), 1),
                "window_grows": st["window_grows"],
                "window_shrinks": st["window_shrinks"],
                "batch_final": st["max_batch"],
            }
            print(f"# {name}: idle_p99={results[name]['idle_p99_ms']}ms "
                  f"flood={results[name]['flood_sigs_per_s']} sigs/s "
                  f"windows={st['batches']} grows={st['window_grows']} "
                  f"shrinks={st['window_shrinks']}", file=sys.stderr)
    finally:
        eng.close(timeout=5)
        os.environ.pop("TM_TPU_FORCE_DEVICE", None)
        _pl.AsyncBatchVerifier._prepare = real_prepare

    ad, sh, dp = (results[k] for k in ("adaptive", "shallow", "deep"))
    # the curve gate: adaptive strictly beats each fixed policy at the
    # end that policy is weak on — deep on idle p99, shallow on relay-
    # command economics (sigs per launch window; raw sigs/s cannot
    # separate the policies under backlog because take-all flushes
    # self-heal a backlogged shallow lane into big launches) — and
    # holds wall-clock throughput/latency tolerance everywhere else
    checks = {
        "beats_deep_at_idle": ad["idle_p99_ms"] < 0.8 * dp["idle_p99_ms"],
        "beats_shallow_at_flood": (
            ad["flood_sigs_per_window"] > 1.3 * sh["flood_sigs_per_window"]),
        "holds_idle_vs_shallow": (
            ad["idle_p99_ms"] <= 1.15 * sh["idle_p99_ms"]),
        "holds_flood_vs_shallow": (
            ad["flood_sigs_per_s"] >= 0.9 * sh["flood_sigs_per_s"]),
        "holds_flood_vs_deep": (
            ad["flood_sigs_per_s"] >= 0.85 * dp["flood_sigs_per_s"]),
        "moved_both_directions": (
            ad["window_grows"] >= 1 and ad["window_shrinks"] >= 1),
        "no_pool_leak": leaked == 0,
    }
    ok = all(checks.values())
    out = {
        "schema_version": 1,
        "metric": "lanes_adaptive_flood_sigs_per_s",
        "value": ad["flood_sigs_per_s"],
        "unit": "sigs/s",
        "mode": "mocked-relay",
        "backend": os.environ.get("JAX_PLATFORMS", "") or "cpu",
        "relay_rtt_ms": args.rtt_ms,
        "flood_sigs": args.flood_sigs,
        "idle_sigs": args.idle_sigs,
        "idle_gap_ms": args.idle_gap_ms,
        "lanes_adaptive_idle_p99_ms": ad["idle_p99_ms"],
        "lanes_adaptive_sigs_per_window": ad["flood_sigs_per_window"],
        "lanes_shallow_flood_sigs_per_s": sh["flood_sigs_per_s"],
        "lanes_shallow_idle_p99_ms": sh["idle_p99_ms"],
        "lanes_shallow_sigs_per_window": sh["flood_sigs_per_window"],
        "lanes_deep_flood_sigs_per_s": dp["flood_sigs_per_s"],
        "lanes_deep_idle_p99_ms": dp["idle_p99_ms"],
        "adaptive_window_grows": ad["window_grows"],
        "adaptive_window_shrinks": ad["window_shrinks"],
        "adaptive_batch_final": ad["batch_final"],
        "policies": results,
        "checks": checks,
        "ok": ok,
        "pool_slots_leaked": leaked,
    }
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1)
            fh.write("\n")
    if not ok:
        sys.exit(1)


def soak_main(argv) -> None:
    """`bench.py soak` — one cluster, all four workloads, SLO verdict
    (ISSUE 16).

    Runs the simnet soak harness (tendermint_tpu/simnet/soak.py): a live
    consensus cluster drives commit-echo verification, light-client
    request fleets, signed-tx floods through a partition/heal fault, and
    a crash-rejoin catch-up — all through ONE shared AsyncBatchVerifier
    — for a configurable virtual duration, with time-series telemetry
    sampled on the virtual clock and declarative per-lane SLO budgets
    evaluated at the end. The relay is MOCKED by default
    (mock_mempool_prepare: real packing, host prep and transfer; the
    launch's all-accept verdict matures rtt_ms after launch), so the
    bench measures the harness and the QoS queue, not kernel time;
    --real runs live kernels.

    Prints ONE JSON summary line; --out writes the FULL artifact
    (SOAK_r*.json, schema_version 1: per-lane latency percentiles over
    time windows, gauge time series, final SLO verdict — rendered by
    tools/soak_report.py, gated by tools/bench_report.py --compare).
    Exits nonzero when the verdict is not green."""
    import argparse

    ap = argparse.ArgumentParser(prog="bench.py soak")
    ap.add_argument("--duration", type=float, default=30.0,
                    help="virtual seconds of combined load (default 30)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--catchup-at", type=int, default=0,
                    help="hold the catch-up replay until the live tip "
                    "reaches this height, so the node rejoins N heights "
                    "behind (0 = chase immediately)")
    ap.add_argument("--sample-s", type=float, default=1.0,
                    help="telemetry sampler cadence, virtual s (default 1)")
    ap.add_argument("--rtt-ms", type=float, default=4.0,
                    help="mocked relay round-trip per launch (default 4)")
    ap.add_argument("--real", action="store_true",
                    help="run live kernels instead of the mocked relay")
    ap.add_argument("--max-wall-s", type=float, default=1800.0)
    ap.add_argument("--out", default="",
                    help="also write the full artifact JSON to this path")
    args = ap.parse_args(argv)

    from tendermint_tpu.libs import jaxcache

    import jax

    jaxcache.enable(jax, os.path.dirname(os.path.abspath(__file__)))

    from tendermint_tpu.ops import pipeline as _pl
    from tendermint_tpu.ops._testing import drain_pool, mock_mempool_prepare
    from tendermint_tpu.simnet.soak import SoakConfig, SoakDriver

    real_prepare = _pl.AsyncBatchVerifier._prepare
    if not args.real:
        _pl.AsyncBatchVerifier._prepare = staticmethod(
            mock_mempool_prepare(real_prepare, args.rtt_ms / 1e3)
        )
        os.environ["TM_TPU_FORCE_DEVICE"] = "1"
    v = _pl.AsyncBatchVerifier(depth=2)
    try:
        cfg = SoakConfig.from_env(
            duration_s=args.duration, seed=args.seed, n_nodes=args.nodes,
            sample_every_s=args.sample_s, max_wall_s=args.max_wall_s,
            catchup_at_height=args.catchup_at or None,
        )
        rec = SoakDriver(v, cfg).run()
        leaked = None
        if not args.real:
            drain_pool(v._pool)
            leaked = v._pool.stats()["in_flight"]
    finally:
        v.close()
        if not args.real:
            os.environ.pop("TM_TPU_FORCE_DEVICE", None)
        _pl.AsyncBatchVerifier._prepare = real_prepare

    rec["mode"] = "real" if args.real else "mocked-relay"
    rec["relay_rtt_ms"] = args.rtt_ms if not args.real else None
    rec["backend"] = os.environ.get("JAX_PLATFORMS", "") or "cpu"
    rec["pool_slots_leaked"] = leaked
    # the ratchet block (tools/bench_report.py SOAK kind): direction-
    # aware compare keys — the p99s regress on RISE, heights/s on FALL
    lp = rec.get("lane_percentiles", {})
    rec["metric"] = "soak_slo_ok"
    rec["value"] = 1 if rec["ok"] else 0
    rec["unit"] = "verdict"
    rec["consensus_commit_p99_ms"] = lp.get("consensus", {}).get("p99_ms")
    rec["light_verdict_p99_ms"] = lp.get("light", {}).get("p99_ms")
    rec["ingress_admission_p99_ms"] = lp.get("ingress", {}).get("p99_ms")
    summary = {
        k: rec.get(k)
        for k in (
            "schema_version", "metric", "value", "unit", "ok", "reason",
            "mode", "relay_rtt_ms", "backend", "seed", "duration_s",
            "virtual_s", "wall_s", "heights", "sampler_ticks",
            "consensus_commit_p99_ms", "light_verdict_p99_ms",
            "ingress_admission_p99_ms", "replay_heights_per_s",
            "pool_slots_leaked",
        )
    }
    print(json.dumps(summary, default=str))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(rec, fh, indent=1, default=str)
            fh.write("\n")
    if not rec["ok"] or leaked:
        sys.exit(1)


if __name__ == "__main__":
    if sys.argv[1:2] == ["multichip"]:
        multichip_main(sys.argv[2:])
    elif sys.argv[1:2] == ["light"]:
        light_main(sys.argv[2:])
    elif sys.argv[1:2] == ["mempool"]:
        mempool_main(sys.argv[2:])
    elif sys.argv[1:2] == ["blocksync"]:
        blocksync_main(sys.argv[2:])
    elif sys.argv[1:2] == ["votes"]:
        votes_main(sys.argv[2:])
    elif sys.argv[1:2] == ["schemes"]:
        schemes_main(sys.argv[2:])
    elif sys.argv[1:2] == ["bls"]:
        bls_main(sys.argv[2:])
    elif sys.argv[1:2] == ["lanes"]:
        lanes_main(sys.argv[2:])
    elif sys.argv[1:2] == ["soak"]:
        soak_main(sys.argv[2:])
    elif os.environ.get("TM_TPU_BENCH_WORKER") == "1":
        worker()
    else:
        main()
