"""Pipeline microbench: where do the ~35ms/batch of non-kernel overhead go?

Measures, on the live TPU:
  t_prep     host prepare_compact (pack + challenges + transposes)
  t_put      host->device transfer of one batch's args
  t_fetch    device->host fetch of the (1, N) verdict
  pipelined  N batches with prep on a feeder thread, args device_put'd
             ahead, deep in-flight queue — the production shape
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.libs import jaxcache  # noqa: E402

jaxcache.set_env(os.environ, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    import jax

    print(f"backend={jax.default_backend()}", flush=True)
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.ops import pallas_verify as pv

    n = 10240
    entries = []
    for i in range(n):
        sk = ed25519.gen_priv_key(i.to_bytes(32, "little"))
        msg = i.to_bytes(8, "big") + b"\x08\x02\x10\x01" + b"p" * 100
        entries.append((sk.pub_key().bytes(), msg, sk.sign(msg)))

    f = pv._jitted_pallas_verify(n, pv.BLOCK, False)
    args = pv.prepare_compact(entries, n)
    out = np.asarray(f(*args))  # warm compile
    assert bool(out.all())

    for _ in range(2):
        t0 = time.perf_counter()
        args = pv.prepare_compact(entries, n)
        t_prep = time.perf_counter() - t0

        t0 = time.perf_counter()
        dev_args = [jax.device_put(a) for a in args]
        jax.block_until_ready(dev_args)
        t_put = time.perf_counter() - t0

        o = f(*dev_args)
        jax.block_until_ready(o)
        t0 = time.perf_counter()
        _ = np.asarray(o)
        t_fetch = time.perf_counter() - t0
        print(f"prep={t_prep*1e3:.1f}ms put={t_put*1e3:.1f}ms fetch={t_fetch*1e3:.1f}ms", flush=True)

    # dispatch with numpy args (transfer inside dispatch) back-to-back
    for reps in (8,):
        t0 = time.perf_counter()
        outs = [f(*args) for _ in range(reps)]
        jax.block_until_ready(outs)
        dt = time.perf_counter() - t0
        print(f"numpy-arg reps={reps}: {dt*1000/reps:.1f} ms/batch "
              f"{reps*n/dt:.0f} sigs/s", flush=True)

    # production shape: feeder thread preps + device_puts, main dispatches
    from concurrent.futures import ThreadPoolExecutor

    def prep_put():
        a = pv.prepare_compact(entries, n)
        return [jax.device_put(x) for x in a]

    for depth in (2, 4):
        n_batches = 12
        with ThreadPoolExecutor(1) as ex:
            t0 = time.perf_counter()
            nxt = ex.submit(prep_put)
            inflight = []
            for i in range(n_batches):
                dev_args = nxt.result()
                if i + 1 < n_batches:
                    nxt = ex.submit(prep_put)
                inflight.append(f(*dev_args))
                if len(inflight) > depth:
                    np.asarray(inflight.pop(0))
            for o in inflight:
                np.asarray(o)
            dt = time.perf_counter() - t0
        print(f"pipelined depth={depth}: {dt*1000/n_batches:.1f} ms/batch "
              f"{n_batches*n/dt:.0f} sigs/s", flush=True)




def main2() -> None:
    import jax
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.ops import pallas_verify as pv

    n = 10240
    entries = []
    for i in range(n):
        sk = ed25519.gen_priv_key(i.to_bytes(32, "little"))
        msg = i.to_bytes(8, "big") + b"\x08\x02\x10\x01" + b"p" * 100
        entries.append((sk.pub_key().bytes(), msg, sk.sign(msg)))
    f = pv._jitted_pallas_verify(n, pv.BLOCK, False)
    args = pv.prepare_compact(entries, n)
    np.asarray(f(*args))  # warm

    from concurrent.futures import ThreadPoolExecutor

    # production shape + async D2H: feeder preps numpy args, main thread
    # dispatches with numpy args (async H2D), starts async copy-to-host,
    # blocks only on batches `depth` behind.
    for depth in (3, 6):
        n_batches = 16
        with ThreadPoolExecutor(1) as ex:
            t0 = time.perf_counter()
            nxt = ex.submit(pv.prepare_compact, entries, n)
            inflight = []
            for i in range(n_batches):
                a = nxt.result()
                if i + 1 < n_batches:
                    nxt = ex.submit(pv.prepare_compact, entries, n)
                o = f(*a)
                try:
                    o.copy_to_host_async()
                except Exception as e:
                    print(f"copy_to_host_async unavailable: {e}")
                inflight.append(o)
                if len(inflight) > depth:
                    assert np.asarray(inflight.pop(0)).all()
            for o in inflight:
                np.asarray(o)
            dt = time.perf_counter() - t0
        print(f"async-d2h depth={depth}: {dt*1000/n_batches:.1f} ms/batch "
              f"{n_batches*n/dt:.0f} sigs/s", flush=True)


if __name__ == "__main__" and os.environ.get("KB2") == "2":
    main2()
elif __name__ == "__main__":
    main()
