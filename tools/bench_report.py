#!/usr/bin/env python
"""bench_report — validate, tabulate and diff the BENCH/MULTICHIP artifacts.

The repo's perf record is the committed `BENCH_r*.json` / `MULTICHIP_r*.
json` files, but their schemas drifted across rounds (driver wrappers,
rc-only failures, a direct artifact in r06) until the cross-PR trajectory
was unextractable. This tool (ISSUE 10 tentpole piece 3) makes the record
mechanical again:

    # schema-check every committed artifact (tier-1 wires this)
    python tools/bench_report.py --validate

    # one row per round: the cross-PR perf trajectory
    python tools/bench_report.py --trajectory

    # diff two artifacts with a percentage regression gate
    python tools/bench_report.py --compare BENCH_r04.json BENCH_r05.json \\
        --gate-pct 10

Canonical schema (SCHEMA_VERSION 1) — what `normalize()` maps EVERY
historical shape onto (the committed artifacts are never rewritten):

    {"schema_version": 1, "kind": "bench"|"multichip", "round": N,
     "ok": bool, "metric": str|None, "value": float|None, "unit": str,
     "metrics": {canonical_key: number, ...}, "notes": [str, ...]}

Known historical shapes:
  * driver wrapper  {"n", "cmd", "rc", "tail", "parsed"}  (BENCH r01+;
    `parsed` is the bench JSON line, None when the round's bench crashed)
  * multichip wrapper  {"n_devices", "ok", "rc", "skipped", "tail"}
    (MULTICHIP r01-r05 — pass/fail smoke, no rates)
  * direct artifact  {"metric", "value", ...}  (MULTICHIP r06+, bench.py
    output lines, `bench.py multichip --out`)

Exit codes: 0 clean, 1 validation failure / regression past the gate,
2 usage error. Pure stdlib — runs without jax, numpy or any crypto wheel.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

SCHEMA_VERSION = 1

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Canonical numeric metric keys, plus the legacy aliases that map onto
# them (the satellite normalizer: old keys → canonical, artifacts stay
# untouched on disk). Higher-is-better unless listed in _LOWER_IS_BETTER.
KEY_ALIASES: Dict[str, str] = {
    # identity for every current bench.py key happens by default; aliases:
    "device_sigs_per_s": "value",
    "sigs_per_s": "value",
    "speedup": "vs_baseline",
}

# numeric keys carried into metrics{} when present (after aliasing)
METRIC_KEYS = (
    "value", "vs_baseline", "host_sigs_per_s", "host_multicore_sigs_per_s",
    "vs_host_multicore", "host_batch_sigs_per_s", "vs_host_batch",
    "kernel_vs_host_batch", "single_commit_sigs_per_s",
    "single_commit_vs_baseline", "relay_rtt_ms", "kernel_stream_sigs_per_s",
    "sustained_sigs_per_s", "sustained_vs_baseline", "mixed_curve_sigs_per_s",
    "pipelined_headers_per_s", "simnet_commits_per_s",
    "simnet_churn_commits_per_s", "speedup_2v1", "n_devices",
    # light-service artifacts (LIGHT_r*, ISSUE 11)
    "light_unique_headers_per_s", "light_sequential_headers_per_s",
    "vs_sequential", "memo_hit_ratio",
    # mempool-ingress artifacts (MEMPOOL_r*, ISSUE 13)
    "mempool_seq_sigs_per_s", "commit_p99_unloaded_ms",
    "commit_p99_flood_ms", "flood_latency_ratio", "checktx_preemptions",
    "ingress_windows", "ingress_batch_wait_ms_avg",
    # chain-replay artifacts (BLOCKSYNC_r*, ISSUE 14)
    "replay_seq_heights_per_s", "kernel_serial_heights_per_s",
    "vs_kernel_serial", "range_hit_rate", "fallback_ranges",
    # live-vote-ingress artifacts (VOTES_r*, ISSUE 15)
    "votes_seq_votes_per_s", "window_dups", "memo_hits",
    # soak-harness artifacts (SOAK_r*, ISSUE 16)
    "consensus_commit_p99_ms", "light_verdict_p99_ms",
    "ingress_admission_p99_ms", "replay_heights_per_s",
    # ingress-fabric curve artifacts (LANES_r*, ISSUE 17); the headline
    # "value" is the adaptive policy's flood sigs/s
    "lanes_adaptive_idle_p99_ms", "lanes_adaptive_sigs_per_window",
    "lanes_shallow_flood_sigs_per_s", "lanes_shallow_idle_p99_ms",
    "lanes_shallow_sigs_per_window", "lanes_deep_flood_sigs_per_s",
    "lanes_deep_idle_p99_ms", "adaptive_window_grows",
    "adaptive_window_shrinks",
    # verification-fleet scale-out artifacts (FLEET_r*, ISSUE 18); the
    # headline "value" is the aggregate sigs/s at the largest host count
    "clients",
    # scheme-lane artifacts (SCHEMES_r*, ISSUE 19); the headline "value"
    # is counted secp256k1 commit sigs/s through ONE relay launch
    "secp_seq_sigs_per_s", "vs_per_sig", "launches", "sigs_counted",
    # aggregation-lane artifacts (AGG_r*, ISSUE 20); the headline "value"
    # is aggregated BLS commits/s through the fused multi-pairing launch
    "pairings_per_commit", "sigs_replaced_per_pairing",
    "wire_ratio_vs_ed25519", "agg_wire_bytes", "ed25519_wire_bytes",
    "commits",
)

# gate semantics: for these, SMALLER is better (a rise is the regression)
_LOWER_IS_BETTER = {
    "relay_rtt_ms", "commit_p99_unloaded_ms", "commit_p99_flood_ms",
    "flood_latency_ratio", "fallback_ranges",
    # soak lane p99s regress on a RISE; replay_heights_per_s (a rate)
    # stays in the default higher-is-better direction
    "consensus_commit_p99_ms", "light_verdict_p99_ms",
    "ingress_admission_p99_ms",
    # lanes-curve idle latencies regress on a RISE
    "lanes_adaptive_idle_p99_ms", "lanes_shallow_idle_p99_ms",
    "lanes_deep_idle_p99_ms",
    # aggregation-lane economics regress on a RISE: more pairings per
    # commit or more wire bytes than the pinned round
    "pairings_per_commit", "wire_ratio_vs_ed25519",
}

# keys a COMPARE tracks by default (rate-like, present across most rounds)
COMPARE_KEYS = (
    "value", "sustained_sigs_per_s", "kernel_stream_sigs_per_s",
    "pipelined_headers_per_s", "mixed_curve_sigs_per_s", "relay_rtt_ms",
    "speedup_2v1", "light_unique_headers_per_s", "flood_latency_ratio",
    "vs_kernel_serial", "consensus_commit_p99_ms", "light_verdict_p99_ms",
    "ingress_admission_p99_ms", "replay_heights_per_s",
    "lanes_adaptive_idle_p99_ms", "lanes_adaptive_sigs_per_window",
    "vs_per_sig", "pairings_per_commit", "wire_ratio_vs_ed25519",
)

_NAME_RE = re.compile(
    r"(BENCH|MULTICHIP|LIGHT|MEMPOOL|BLOCKSYNC|VOTES|SOAK|LANES|FLEET"
    r"|SCHEMES|AGG)_r(\d+)",
    re.I)


def _round_kind_from_name(path: str):
    m = _NAME_RE.search(os.path.basename(path))
    if not m:
        return None, None
    return m.group(1).lower(), int(m.group(2))


def _num(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def _collect_metrics(src: dict) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for k, v in src.items():
        ck = KEY_ALIASES.get(k, k)
        if ck in METRIC_KEYS:
            n = _num(v)
            if n is not None:
                out[ck] = n
    return out


def normalize(raw: dict, path: str = "") -> dict:
    """Map any committed artifact shape onto the canonical schema."""
    kind, rnd = _round_kind_from_name(path)
    art = {
        "schema_version": SCHEMA_VERSION,
        "kind": kind or "bench",
        "round": rnd,
        "path": os.path.basename(path) if path else "",
        "ok": False,
        "metric": None,
        "value": None,
        "unit": "",
        "mode": "",
        "backend": "",
        "metrics": {},
        "notes": [],
    }
    if not isinstance(raw, dict):
        art["notes"].append("artifact is not a JSON object")
        return art

    if "parsed" in raw and "cmd" in raw:
        # driver wrapper around a bench.py JSON line
        parsed = raw.get("parsed")
        if rnd is None:
            art["round"] = raw.get("n")
        if not isinstance(parsed, dict):
            art["ok"] = False
            art["notes"].append(
                f"bench run produced no parsed JSON line (rc={raw.get('rc')})"
            )
            return art
        art["ok"] = raw.get("rc", 1) == 0
        src = parsed
    elif "n_devices" in raw and "metric" not in raw:
        # legacy multichip smoke wrapper: pass/fail only
        art["kind"] = kind or "multichip"
        art["ok"] = bool(raw.get("ok")) and not raw.get("skipped")
        art["metrics"] = _collect_metrics(raw)
        art["notes"].append("legacy multichip smoke (compile pass/fail, "
                            "no throughput figures)")
        if not art["ok"]:
            art["notes"].append(f"smoke failed (rc={raw.get('rc')})")
        return art
    elif "metric" in raw:
        # direct artifact (MULTICHIP r06+, bench.py line); soak records
        # carry their own SLO verdict in "ok" — honor it
        art["ok"] = bool(raw.get("ok", True))
        src = raw
    else:
        art["notes"].append("unrecognized artifact shape "
                            f"(keys: {sorted(raw)[:8]})")
        return art

    art["metric"] = src.get("metric")
    art["unit"] = src.get("unit", "")
    art["mode"] = src.get("mode", "")
    art["backend"] = src.get("backend", "")
    art["value"] = _num(src.get("value"))
    art["metrics"] = _collect_metrics(src)
    ss = src.get("span_summary")
    if isinstance(ss, dict):
        # tolerate both pre- and post-ISSUE-10 span summaries: absent
        # stats under {"tracing": false} are NOT an error (the satellite
        # contract — better no number than a misleading 0.0)
        art["span_tracing"] = bool(ss.get("tracing", True))
    if src.get("error"):
        art["ok"] = False
        art["notes"].append(str(src["error"]))
    return art


def load(path: str) -> dict:
    try:
        with open(path) as fh:
            raw = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        art = normalize({}, path)
        art["notes"] = [f"unreadable: {e}"]
        art["unreadable"] = True
        return art
    return normalize(raw, path)


def default_paths(root: str = REPO) -> List[str]:
    paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    paths += sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json")))
    paths += sorted(glob.glob(os.path.join(root, "LIGHT_r*.json")))
    paths += sorted(glob.glob(os.path.join(root, "MEMPOOL_r*.json")))
    paths += sorted(glob.glob(os.path.join(root, "BLOCKSYNC_r*.json")))
    paths += sorted(glob.glob(os.path.join(root, "VOTES_r*.json")))
    paths += sorted(glob.glob(os.path.join(root, "SOAK_r*.json")))
    paths += sorted(glob.glob(os.path.join(root, "LANES_r*.json")))
    paths += sorted(glob.glob(os.path.join(root, "FLEET_r*.json")))
    paths += sorted(glob.glob(os.path.join(root, "SCHEMES_r*.json")))
    paths += sorted(glob.glob(os.path.join(root, "AGG_r*.json")))
    return paths


# ---------------------------------------------------------------------------
# validate
# ---------------------------------------------------------------------------


def validate(art: dict) -> List[str]:
    """Schema problems for one normalized artifact. A FAILED round is a
    valid artifact (the record honestly says the round failed); an
    artifact the normalizer cannot even classify is not."""
    probs: List[str] = []
    if art.get("unreadable"):
        probs.append("; ".join(art["notes"]))
        return probs
    if art["kind"] not in ("bench", "multichip", "light", "mempool",
                           "blocksync", "votes", "soak", "lanes", "fleet",
                           "schemes", "agg"):
        probs.append(f"unknown kind {art['kind']!r}")
    if art["round"] is None:
        probs.append("cannot derive the round number (filename or 'n')")
    if any(n.startswith("unrecognized") for n in art["notes"]):
        probs.append("; ".join(art["notes"]))
    if art["ok"]:
        if art["kind"] == "bench" and _num(art["value"]) is None:
            probs.append("ok bench artifact without a numeric value")
        for k, v in art["metrics"].items():
            if _num(v) is None:
                probs.append(f"non-numeric metric {k}={v!r}")
    return probs


# ---------------------------------------------------------------------------
# trajectory
# ---------------------------------------------------------------------------


def _fmt(v, width=10) -> str:
    if v is None:
        return " " * (width - 1) + "-"
    if abs(v) >= 1000:
        return f"{v:>{width},.0f}"
    return f"{v:>{width}.2f}"


def trajectory_rows(arts: List[dict]) -> List[dict]:
    rows = []
    for art in sorted(arts, key=lambda a: (a["kind"], a["round"] or 0)):
        m = art["metrics"]
        rows.append({
            "kind": art["kind"],
            "round": art["round"],
            "ok": art["ok"],
            "value": art["value"] if art["kind"] == "bench"
            else m.get("value"),
            "sustained": m.get("sustained_sigs_per_s"),
            "kernel_stream": m.get("kernel_stream_sigs_per_s"),
            "headers_per_s": m.get("pipelined_headers_per_s"),
            "rtt_ms": m.get("relay_rtt_ms"),
            "speedup_2v1": m.get("speedup_2v1"),
            "mode": art["mode"],
            "backend": art["backend"],
            "note": art["notes"][0] if art["notes"] else "",
        })
    return rows


def print_trajectory(rows: List[dict]) -> None:
    hdr = (f"{'artifact':<14} {'ok':<4} {'sigs/s':>10} {'sustained':>10} "
           f"{'kernel':>10} {'hdrs/s':>8} {'rtt ms':>7} {'2v1':>6}  "
           f"{'mode/backend':<24} note")
    print(hdr)
    print("-" * len(hdr))
    for r in rows:
        name = f"{r['kind']}_r{r['round']:02d}" if r["round"] is not None \
            else r["kind"]
        mb = "/".join(x for x in (r["mode"], r["backend"]) if x)
        print(f"{name:<14} {'yes' if r['ok'] else 'NO':<4} "
              f"{_fmt(r['value'])} {_fmt(r['sustained'])} "
              f"{_fmt(r['kernel_stream'])} {_fmt(r['headers_per_s'], 8)} "
              f"{_fmt(r['rtt_ms'], 7)} {_fmt(r['speedup_2v1'], 6)}  "
              f"{mb:<24} {r['note'][:48]}")


# ---------------------------------------------------------------------------
# compare
# ---------------------------------------------------------------------------


def compare(a: dict, b: dict, gate_pct: float,
            keys=COMPARE_KEYS) -> dict:
    """Diff two normalized artifacts: per-metric delta %, and the list of
    metrics that regressed past `gate_pct` (direction-aware)."""
    rows = []
    regressions = []
    am = dict(a["metrics"])
    bm = dict(b["metrics"])
    if a["value"] is not None:
        am.setdefault("value", a["value"])
    if b["value"] is not None:
        bm.setdefault("value", b["value"])
    for k in keys:
        va, vb = am.get(k), bm.get(k)
        if va is None or vb is None:
            continue
        delta_pct = ((vb - va) / abs(va) * 100.0) if va else 0.0
        worse = -delta_pct if k not in _LOWER_IS_BETTER else delta_pct
        regressed = worse > gate_pct
        rows.append({
            "metric": k, "a": va, "b": vb,
            "delta_pct": round(delta_pct, 2), "regressed": regressed,
        })
        if regressed:
            regressions.append(k)
    return {
        "a": a.get("path") or f"{a['kind']}_r{a['round']}",
        "b": b.get("path") or f"{b['kind']}_r{b['round']}",
        "gate_pct": gate_pct,
        "rows": rows,
        "regressions": regressions,
        "ok": not regressions,
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_report")
    ap.add_argument("paths", nargs="*",
                    help="artifact files (default: every committed "
                    "BENCH_r*/MULTICHIP_r* at the repo root)")
    ap.add_argument("--validate", action="store_true",
                    help="schema-check the artifacts; exit 1 on problems")
    ap.add_argument("--trajectory", action="store_true",
                    help="print one row per round (the cross-PR record)")
    ap.add_argument("--compare", nargs=2, metavar=("A", "B"),
                    help="diff artifact A (baseline) against B")
    ap.add_argument("--gate-pct", type=float, default=10.0,
                    help="--compare: fail when a tracked metric regresses "
                    "by more than this percentage (default 10)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if args.compare:
        a, b = (load(p) for p in args.compare)
        for art, p in ((a, args.compare[0]), (b, args.compare[1])):
            if art.get("unreadable"):
                print(f"error: {p}: {art['notes'][0]}", file=sys.stderr)
                return 2
        res = compare(a, b, args.gate_pct)
        if args.as_json:
            print(json.dumps(res, indent=2))
        else:
            print(f"{res['a']}  →  {res['b']}   (gate {args.gate_pct}%)")
            for r in res["rows"]:
                flag = "  REGRESSED" if r["regressed"] else ""
                print(f"  {r['metric']:<28} {_fmt(r['a'])} → {_fmt(r['b'])} "
                      f"({r['delta_pct']:+.1f}%){flag}")
            if not res["rows"]:
                print("  (no comparable metrics)")
        return 0 if res["ok"] else 1

    paths = args.paths or default_paths()
    if not paths:
        print("error: no artifacts found", file=sys.stderr)
        return 2
    arts = [load(p) for p in paths]

    rc = 0
    if args.validate or not args.trajectory:
        problems = {a["path"] or p: validate(a)
                    for a, p in zip(arts, paths)}
        bad = {k: v for k, v in problems.items() if v}
        if args.as_json:
            print(json.dumps({
                "schema_version": SCHEMA_VERSION,
                "checked": len(arts),
                "ok": not bad,
                "problems": bad,
            }, indent=2))
        else:
            for a in arts:
                name = a["path"]
                ps = problems[name or ""] if name in problems else []
                status = "ok" if not ps else "INVALID: " + "; ".join(ps)
                print(f"{name:<22} {status}")
            print(f"{len(arts)} artifact(s), {len(bad)} invalid")
        if bad:
            rc = 1

    if args.trajectory:
        rows = trajectory_rows(arts)
        if args.as_json:
            print(json.dumps(rows, indent=2))
        else:
            print_trajectory(rows)

    return rc


if __name__ == "__main__":
    sys.exit(main())
