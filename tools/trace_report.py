#!/usr/bin/env python
"""Summarize a Chrome-trace dump from the span tracer.

Usage:
    python tools/trace_report.py <trace.json> [--json]

<trace.json> is a Trace Event Format file — what `/dump_trace` returns
under "trace", what the node's OnStop flush writes to
instrumentation.trace_dump_path, or any hand-rolled
observability.trace.TRACER.dump() output. Prints a per-span table
(count, total, p50/p95/p99 ms) plus the wall-clock extent and device
utilization (fraction of wall covered by device-side spans); --json
emits the same summary as one JSON object for scripting.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.observability.trace import summarize_events  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trace_report")
    ap.add_argument("trace_file", help="Chrome-trace JSON file")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the summary as JSON")
    args = ap.parse_args(argv)

    with open(args.trace_file) as fh:
        doc = json.load(fh)
    if "traceEvents" not in doc:
        # tolerate a /dump_trace response body saved verbatim
        doc = doc.get("trace", doc.get("result", {}).get("trace", {}))
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        print("error: no traceEvents found in input", file=sys.stderr)
        return 1

    summary = summarize_events(doc)
    if args.as_json:
        print(json.dumps(summary))
        return 0

    wall = summary.pop("_wall")
    name_w = max([len(n) for n in summary] + [len("span")])
    hdr = (f"{'span':<{name_w}}  {'count':>7}  {'total ms':>10}  "
           f"{'p50 ms':>9}  {'p95 ms':>9}  {'p99 ms':>9}")
    print(hdr)
    print("-" * len(hdr))
    for name, s in sorted(summary.items(),
                          key=lambda kv: -kv[1]["total_ms"]):
        print(f"{name:<{name_w}}  {s['count']:>7}  {s['total_ms']:>10.3f}  "
              f"{s['p50_ms']:>9.3f}  {s['p95_ms']:>9.3f}  {s['p99_ms']:>9.3f}")
    print("-" * len(hdr))
    print(f"wall clock: {wall['wall_ms']:.3f} ms over {wall['events']} events; "
          f"device utilization: {wall['device_utilization'] * 100:.1f}%")
    return 0


if __name__ == "__main__":
    sys.exit(main())
