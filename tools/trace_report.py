#!/usr/bin/env python
"""Summarize (and merge) Chrome-trace dumps from the span tracer.

Usage:
    python tools/trace_report.py <trace.json> [--json] [--top N]
    python tools/trace_report.py --merge a.json b.json ... \\
        [--out merged.json] [--json] [--top N]

Inputs are Trace Event Format files — what `/dump_trace` returns under
"trace", what the node's OnStop flush writes to
instrumentation.trace_dump_path, what `tools/simnet_run.py --trace`
exports (already merged per cluster), or any hand-rolled
observability.trace.TRACER.dump() output. Prints a per-span table
(count, total, p50/p95/p99 ms, sorted by total ms — `--top N` keeps the
N heaviest rows) plus the wall-clock extent, device utilization and the
flow-chain count; --json emits the same summary as one JSON object.

`--merge` (ISSUE 10) re-keys pids and concatenates several documents
into one (written to `--out` when given) before summarizing — the
offline path to a single cluster-wide Perfetto view when per-node traces
were dumped separately; flow ids are preserved so cross-file causal
chains stay linked.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.observability.trace import (  # noqa: E402
    dump_doc,
    flow_chains,
    merge_traces,
    summarize_events,
)


def _load_doc(path: str):
    with open(path) as fh:
        doc = json.load(fh)
    if "traceEvents" not in doc:
        # tolerate a /dump_trace response body saved verbatim
        doc = doc.get("trace", doc.get("result", {}).get("trace", {}))
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return None
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trace_report")
    ap.add_argument("trace_files", nargs="+",
                    help="Chrome-trace JSON file(s); several with --merge")
    ap.add_argument("--merge", action="store_true",
                    help="merge the inputs into one document (pids "
                    "re-keyed, flow ids preserved) before summarizing")
    ap.add_argument("--out", default="",
                    help="with --merge: also write the merged document here")
    ap.add_argument("--top", type=int, default=0,
                    help="only print the N spans heaviest by total ms")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the summary as JSON")
    args = ap.parse_args(argv)

    if len(args.trace_files) > 1 and not args.merge:
        print("error: multiple inputs require --merge", file=sys.stderr)
        return 2

    docs = []
    for path in args.trace_files:
        doc = _load_doc(path)
        if doc is None:
            print(f"error: no traceEvents found in {path}", file=sys.stderr)
            return 1
        docs.append(doc)
    doc = (
        merge_traces(docs, labels=[os.path.basename(p)
                                   for p in args.trace_files])
        if args.merge else docs[0]
    )
    if args.merge and args.out:
        dump_doc(doc, args.out)

    summary = summarize_events(doc)
    chains = flow_chains(doc)
    cross = sum(
        1 for evs in chains.values()
        if len({e.get("pid") for e in evs}) > 1
    )
    if args.as_json:
        summary["_flows"] = {"chains": len(chains), "cross_process": cross}
        print(json.dumps(summary))
        return 0

    wall = summary.pop("_wall")
    rows = sorted(summary.items(), key=lambda kv: -kv[1]["total_ms"])
    dropped = 0
    if args.top and args.top > 0 and len(rows) > args.top:
        dropped = len(rows) - args.top
        rows = rows[: args.top]
    name_w = max([len(n) for n, _ in rows] + [len("span")])
    hdr = (f"{'span':<{name_w}}  {'count':>7}  {'total ms':>10}  "
           f"{'p50 ms':>9}  {'p95 ms':>9}  {'p99 ms':>9}")
    print(hdr)
    print("-" * len(hdr))
    for name, s in rows:
        print(f"{name:<{name_w}}  {s['count']:>7}  {s['total_ms']:>10.3f}  "
              f"{s['p50_ms']:>9.3f}  {s['p95_ms']:>9.3f}  {s['p99_ms']:>9.3f}")
    print("-" * len(hdr))
    if dropped:
        print(f"(… {dropped} lighter span name(s) below --top {args.top})")
    print(f"wall clock: {wall['wall_ms']:.3f} ms over {wall['events']} events; "
          f"device utilization: {wall['device_utilization'] * 100:.1f}%; "
          f"flow chains: {len(chains)} ({cross} cross-process)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
