"""RLC kernel microbench on the live TPU: time the per-lane fast-accept
pipeline (ops/pallas_rlc.py) at full bucket and compare with the per-sig
kernel's batch time. Development tool — not part of the driver protocol."""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.libs import jaxcache  # noqa: E402

jaxcache.set_env(os.environ, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    import jax

    print(f"backend={jax.default_backend()} devices={jax.devices()}", flush=True)
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.ops import pallas_rlc as pr

    n = int(os.environ.get("KB_SIGS", "10240"))
    block = int(os.environ.get("KB_BLOCK", "0")) or pr.BLOCK_LANES
    g = n // pr.M
    entries = []
    for i in range(n):
        sk = ed25519.gen_priv_key(i.to_bytes(32, "little"))
        msg = i.to_bytes(8, "big") + b"\x08\x02\x10\x01" + b"p" * 100
        entries.append((sk.pub_key().bytes(), msg, sk.sign(msg)))
    t0 = time.perf_counter()
    args = pr.prepare_rlc(entries, n)
    print(f"prep={time.perf_counter()-t0:.3f}s  M={pr.M} lanes={g} block={block}",
          flush=True)

    f = pr._jitted_rlc_verify(g, block, False)
    t0 = time.perf_counter()
    out = np.asarray(f(*args))
    print(f"warm(compile)={time.perf_counter()-t0:.1f}s ok={bool(out.all())}",
          flush=True)
    assert bool(out.all())

    args_dev = [jax.device_put(a) for a in args]
    for reps in (1, 4, 8):
        t0 = time.perf_counter()
        outs = [f(*args_dev) for _ in range(reps)]
        jax.block_until_ready(outs)
        dt = time.perf_counter() - t0
        print(f"reps={reps}: {dt*1000/reps:.1f} ms/batch  "
              f"{reps*n/dt:.0f} sigs/s", flush=True)


if __name__ == "__main__":
    main()
