#!/usr/bin/env python3
"""simnet_run — drive a deterministic in-process consensus cluster.

Runs N real consensus nodes over the simnet virtual network with a fault
schedule, checks the Tendermint safety invariants live, and emits a JSON
verdict (and optionally a Chrome-trace span file from the observability
tracer). Same --seed ⇒ byte-identical run; a failing seed IS the repro.

Examples:
    # 4 nodes to height 20, defaults
    python tools/simnet_run.py --height 20

    # the tier-1 smoke: partition-and-heal + crash/WAL-restart, run twice,
    # assert replay-exact fingerprints
    python tools/simnet_run.py --smoke

    # a custom schedule + lossy links, with a trace
    python tools/simnet_run.py --seed 9 --faults sched.json \\
        --drop 0.05 --jitter-ms 20 --trace /tmp/simnet-trace.json

Fault schedule JSON: see tendermint_tpu/simnet/faults.py docstring.
Runs on CPU without the `cryptography` wheel (pure-Python ed25519
fallback), without TCP, and without a TPU.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
try:  # containers without the OpenSSL wheel run the pure-Python signer
    import cryptography  # noqa: F401
except ModuleNotFoundError:
    os.environ.setdefault("TM_TPU_PUREPY_CRYPTO", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE_SEED = 42
SMOKE_HEIGHT = 20  # the acceptance bar: partition+heal+crash/restart to h>=20


def build_cluster(args, faults):
    from tendermint_tpu.simnet import Cluster, LinkConfig

    link = LinkConfig(
        latency_s=args.latency_ms / 1000.0,
        jitter_s=args.jitter_ms / 1000.0,
        drop=args.drop,
        duplicate=args.duplicate,
        reorder=args.reorder,
        bandwidth_bps=args.bandwidth_bps or None,
    )
    return Cluster(
        n_nodes=args.nodes,
        seed=args.seed,
        link=link,
        faults=faults,
        txs_per_node=args.txs,
    )


def load_faults(args):
    from tendermint_tpu.simnet import (
        crash_restart_schedule,
        parse_faults,
        partition_heal_schedule,
        smoke_schedule,
    )

    if args.faults:
        with open(args.faults) as fh:
            return parse_faults(json.load(fh))
    preset = args.preset
    if preset == "partition_heal":
        return partition_heal_schedule(args.nodes)
    if preset == "crash_restart":
        return crash_restart_schedule(args.nodes - 1)
    if preset == "smoke":
        return smoke_schedule(args.nodes)
    return []


def run_once(args, faults) -> dict:
    from tendermint_tpu.observability import trace as _trace

    cluster = build_cluster(args, faults)
    try:
        with _trace.span("simnet.run", seed=args.seed, nodes=args.nodes):
            rep = cluster.run_to_height(args.height, max_virtual_s=args.max_virtual_s)
    finally:
        cluster.stop()  # closes WALs and removes the temp dir even on error
    out = rep.to_dict()
    out["commits_per_s"] = (
        round(rep.height / rep.wall_s, 2) if rep.wall_s > 0 else None
    )
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--height", type=int, default=20)
    ap.add_argument("--max-virtual-s", type=float, default=600.0)
    ap.add_argument("--faults", default="", help="JSON fault schedule file")
    ap.add_argument(
        "--preset",
        choices=["none", "partition_heal", "crash_restart", "smoke"],
        default="none",
    )
    ap.add_argument("--txs", type=int, default=0, help="seed N txs per node")
    ap.add_argument("--latency-ms", type=float, default=5.0)
    ap.add_argument("--jitter-ms", type=float, default=0.0)
    ap.add_argument("--drop", type=float, default=0.0)
    ap.add_argument("--duplicate", type=float, default=0.0)
    ap.add_argument("--reorder", type=float, default=0.0)
    ap.add_argument("--bandwidth-bps", type=float, default=0.0)
    ap.add_argument("--trace", default="", help="write Chrome-trace spans here")
    ap.add_argument(
        "--repeat",
        type=int,
        default=1,
        help="run N times with the same seed and require identical fingerprints",
    )
    ap.add_argument(
        "--smoke",
        action="store_true",
        help=f"tier-1 smoke: 4 nodes, smoke schedule, seed {SMOKE_SEED}, "
        f"height {SMOKE_HEIGHT}, two replay-exact runs",
    )
    args = ap.parse_args()

    if args.smoke:
        args.nodes = 4
        args.seed = SMOKE_SEED
        args.height = max(args.height if args.height != 20 else 0, SMOKE_HEIGHT)
        args.preset = "smoke"
        args.repeat = max(args.repeat, 2)

    from tendermint_tpu.observability import trace as _trace

    if args.trace:
        _trace.configure(enabled=True)

    t0 = time.monotonic()
    faults = load_faults(args)
    runs = [run_once(args, load_faults(args)) for _ in range(max(args.repeat, 1))]
    verdict = dict(runs[0])
    verdict["runs"] = len(runs)
    verdict["wall_total_s"] = round(time.monotonic() - t0, 3)
    verdict["replay_exact"] = all(
        r["fingerprint"] == runs[0]["fingerprint"]
        and r["schedule_digest"] == runs[0]["schedule_digest"]
        for r in runs
    )
    if len(runs) > 1 and not verdict["replay_exact"]:
        verdict["ok"] = False
        verdict["reason"] = "same-seed runs diverged (replay exactness broken)"
    verdict["faults"] = [f.kind for f in faults]

    if args.trace:
        path = _trace.TRACER.dump(args.trace)
        verdict["trace_path"] = path

    print(json.dumps(verdict, indent=2, default=str))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
