#!/usr/bin/env python3
"""simnet_run — drive a deterministic in-process consensus cluster.

Runs N real consensus nodes over the simnet virtual network with a fault
schedule, checks the Tendermint safety invariants live, and emits a JSON
verdict (and optionally a Chrome-trace span file from the observability
tracer). Same --seed ⇒ byte-identical run; a failing seed IS the repro.

Examples:
    # 4 nodes to height 20, defaults
    python tools/simnet_run.py --height 20

    # the tier-1 smoke: partition-and-heal + crash/WAL-restart, run twice,
    # assert replay-exact fingerprints
    python tools/simnet_run.py --smoke

    # 100-node cluster, 12 active validators, rotation every 5 heights,
    # two replay-exact runs
    python tools/simnet_run.py --nodes 100 --validators 12 \\
        --preset rotation --rotate-every 5 --height 20 --repeat 2

    # property-based schedule search: seeds x generators until an
    # invariant breaks, then shrink the failing schedule to a minimal
    # JSON regression scenario
    python tools/simnet_run.py --search --search-seeds 0:20 \\
        --nodes 8 --height 12 --scenario-dir tests/scenarios

    # replay a recorded regression scenario
    python tools/simnet_run.py --scenario tests/scenarios/foo.json

Fault schedule JSON: see tendermint_tpu/simnet/faults.py docstring.
Runs on CPU without the `cryptography` wheel (pure-Python ed25519
fallback), without TCP, and without a TPU.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
try:  # containers without the OpenSSL wheel run the pure-Python signer
    import cryptography  # noqa: F401
except ModuleNotFoundError:
    os.environ.setdefault("TM_TPU_PUREPY_CRYPTO", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE_SEED = 42
SMOKE_HEIGHT = 20  # the acceptance bar: partition+heal+crash/restart to h>=20


def build_cluster(args, faults, link=None, tracing=None):
    from tendermint_tpu.simnet import Cluster, LinkConfig

    if link is None:
        link = LinkConfig(
            latency_s=args.latency_ms / 1000.0,
            jitter_s=args.jitter_ms / 1000.0,
            drop=args.drop,
            duplicate=args.duplicate,
            reorder=args.reorder,
            bandwidth_bps=args.bandwidth_bps or None,
        )
    return Cluster(
        n_nodes=args.nodes,
        seed=args.seed,
        link=link,
        faults=faults,
        txs_per_node=args.txs,
        n_validators=args.validators or None,
        tracing=tracing,
        vote_ingress=getattr(args, "vote_ingress", None) or None,
    )


def load_faults(args):
    from tendermint_tpu.simnet import (
        crash_restart_schedule,
        parse_faults,
        partition_heal_schedule,
        rotation_schedule,
        smoke_schedule,
    )

    if args.faults:
        with open(args.faults) as fh:
            return parse_faults(json.load(fh))
    preset = args.preset
    if preset == "partition_heal":
        return partition_heal_schedule(args.nodes)
    if preset == "crash_restart":
        return crash_restart_schedule(args.nodes - 1)
    if preset == "smoke":
        return smoke_schedule(args.nodes)
    if preset == "rotation":
        return rotation_schedule(
            args.nodes,
            args.validators or args.nodes,
            every=args.rotate_every,
            start=args.rotate_start,
            until=args.height,
        )
    return []


def run_once(args, faults, link=None, want_trace=False) -> tuple:
    """One cluster run; returns (verdict_dict, merged_trace_doc_or_None).
    The merged doc (ISSUE 10) is the CLUSTER export — per-node
    virtual-clock tracers + the driver's wall-clock spans, flow chains
    intact — not just the process-wide ring."""
    from tendermint_tpu.observability import trace as _trace

    # per-node tracing only where the doc is actually kept: with --trace
    # --repeat N, runs 1..N-1 force it OFF instead of paying full span
    # recording for buffers that are discarded (tracing never perturbs a
    # run, so replay-exactness across the repeats is unaffected)
    cluster = build_cluster(
        args, faults, link=link,
        tracing=want_trace if args.trace else None,
    )
    if getattr(args, "replay_node", -1) >= 0:
        from tendermint_tpu.simnet import CatchupDriver

        rdrop = getattr(args, "replay_drop", -1.0)
        CatchupDriver(
            cluster, args.replay_node,
            drop=rdrop if rdrop >= 0 else args.drop,
            start_after=5.0,
            start_at_height=getattr(args, "replay_at", 0) or None,
        )
    merged = None
    try:
        with _trace.span("simnet.run", seed=args.seed, nodes=args.nodes):
            rep = cluster.run_to_height(
                args.height,
                max_virtual_s=args.max_virtual_s,
                max_wall_s=_wall_budget(args, None),
            )
        if want_trace:
            merged = cluster.export_merged_trace()
    finally:
        cluster.stop()  # closes WALs and removes the temp dir even on error
    out = rep.to_dict()
    out["commits_per_s"] = (
        round(rep.height / rep.wall_s, 2) if rep.wall_s > 0 else None
    )
    return out, merged


def _wall_budget(args, mode_default):
    """-1 = mode default, 0 = explicitly unbounded, else the bound."""
    if args.max_wall_s < 0:
        return mode_default
    return args.max_wall_s or None


def _attach_devcheck(verdict: dict) -> None:
    """Embed the runtime-checker report; any violation fails the run."""
    from tendermint_tpu.libs import devcheck

    rep = devcheck.report()
    verdict["devcheck"] = rep
    if rep["violations"]:
        verdict["ok"] = False
        verdict["reason"] = (
            f"{len(rep['violations'])} devcheck violation(s): "
            + "; ".join(v["message"] for v in rep["violations"][:3])
        )


def run_soak(args) -> int:
    """--soak: one cluster, all four QoS workloads, time-series telemetry
    and a declarative SLO verdict (ISSUE 16). The verify engine runs with
    the relay MOCKED by default (real packing/prep/transfer, all-accept
    verdict behind --soak-rtt-ms) so CI boxes measure the harness and the
    SLOs, not jax compile time; --soak-real runs live kernels. Exit 0 on
    a green verdict, 1 on any conclusive failure (SLO breach, invariant,
    devcheck), 3 when the wall budget cut the run short (inconclusive —
    the same classification --scenario applies)."""
    from tendermint_tpu.ops import pipeline as _pl
    from tendermint_tpu.simnet.soak import SoakConfig, SoakDriver

    real_prepare = _pl.AsyncBatchVerifier._prepare
    force_prev = os.environ.get("TM_TPU_FORCE_DEVICE")
    if not args.soak_real:
        from tendermint_tpu.ops._testing import mock_mempool_prepare

        _pl.AsyncBatchVerifier._prepare = staticmethod(
            mock_mempool_prepare(real_prepare, args.soak_rtt_ms / 1e3)
        )
        os.environ["TM_TPU_FORCE_DEVICE"] = "1"
    t0 = time.monotonic()
    runs = []
    try:
        for _ in range(max(args.repeat, 1)):
            v = _pl.AsyncBatchVerifier(depth=2)
            try:
                cfg = SoakConfig.from_env(
                    duration_s=args.soak,
                    seed=args.seed,
                    n_nodes=args.nodes,
                    catchup_at_height=getattr(args, "replay_at", 0) or None,
                    max_wall_s=_wall_budget(args, 300.0),
                )
                runs.append(SoakDriver(v, cfg).run())
            finally:
                v.close()
    finally:
        _pl.AsyncBatchVerifier._prepare = real_prepare
        if not args.soak_real:
            if force_prev is None:
                os.environ.pop("TM_TPU_FORCE_DEVICE", None)
            else:
                os.environ["TM_TPU_FORCE_DEVICE"] = force_prev
    verdict = dict(runs[0])
    verdict["mode"] = "real" if args.soak_real else "mocked-relay"
    verdict["relay_rtt_ms"] = None if args.soak_real else args.soak_rtt_ms
    verdict["runs"] = len(runs)
    verdict["wall_total_s"] = round(time.monotonic() - t0, 3)
    verdict["replay_exact"] = all(
        r["fingerprint"] == runs[0]["fingerprint"]
        and r["schedule_digest"] == runs[0]["schedule_digest"]
        for r in runs
    )
    if len(runs) > 1 and not verdict["replay_exact"]:
        verdict["ok"] = False
        verdict["reason"] = (
            "same-seed soak runs diverged (replay exactness broken)"
        )
    if args.devcheck:
        _attach_devcheck(verdict)
    if args.soak_out:
        with open(args.soak_out, "w") as fh:
            json.dump(verdict, fh, indent=1, default=str)
            fh.write("\n")
    # stdout stays readable: the bulky rings live only in --soak-out
    slim = {
        k: v for k, v in verdict.items()
        if k not in ("gauges", "windows", "verify_engine", "flight_recorder")
    }
    print(json.dumps(slim, indent=2, default=str))
    if verdict["ok"]:
        return 0
    inconclusive = (
        verdict.get("wall_budget_hit")
        and verdict.get("reason") == "wall budget exhausted"
        and not (verdict.get("devcheck") or {}).get("violations")
    )
    return 3 if inconclusive else 1


def run_fleet(args) -> int:
    """--fleet: the shared-verification-fleet scenario (ISSUE 18). A
    100-node cluster submits EntryBlock verify requests at all three QoS
    tiers through the real wire codec (loopback transport) to ONE fleet
    host; --fleet-kill-at crashes it mid-run and every node degrades to
    local verification with zero stalled requests. --repeat N asserts
    replay-exact reports; the verdict also checks verdict parity against
    an all-local run of the same seed (degradation may move WHERE a
    verdict is computed, never what it is). Pure host-side — no jax, no
    crypto wheel."""
    from tendermint_tpu.simnet.fleet import run_fleet_scenario

    kw = dict(
        seed=args.seed,
        n_nodes=args.fleet_nodes,
        kill_at=args.fleet_kill_at if args.fleet_kill_at >= 0 else None,
        revive_at=args.fleet_revive_at if args.fleet_revive_at >= 0 else None,
    )
    t0 = time.monotonic()
    runs = [run_fleet_scenario(**kw) for _ in range(max(args.repeat, 1))]
    baseline = run_fleet_scenario(seed=args.seed, n_nodes=args.fleet_nodes,
                                  all_local=True)
    verdict = dict(runs[0])
    verdict["runs"] = len(runs)
    verdict["wall_total_s"] = round(time.monotonic() - t0, 3)
    verdict["replay_exact"] = all(r == runs[0] for r in runs)
    verdict["verdict_parity"] = (
        runs[0]["verdict_fingerprint"] == baseline["verdict_fingerprint"]
    )
    verdict["ok"] = bool(
        verdict["replay_exact"]
        and verdict["verdict_parity"]
        and verdict["stalled_requests"] == 0
    )
    if not verdict["ok"]:
        verdict["reason"] = (
            "same-seed fleet runs diverged" if not verdict["replay_exact"]
            else "fleet/local verdict streams differ"
            if not verdict["verdict_parity"]
            else "%d requests stalled" % verdict["stalled_requests"]
        )
    print(json.dumps(verdict, indent=2, default=str))
    return 0 if verdict["ok"] else 1


def parse_seed_range(spec: str):
    """"a:b" -> range(a, b); "3,7,9" -> [3, 7, 9]; "12" -> [12]."""
    if ":" in spec:
        a, b = spec.split(":", 1)
        return list(range(int(a), int(b)))
    return [int(s) for s in spec.split(",") if s.strip() != ""]


def run_search(args) -> int:
    from tendermint_tpu.simnet.search import GENERATORS, search_schedules

    seeds = parse_seed_range(args.search_seeds)
    generators = [g for g in args.generators.split(",") if g]
    # an empty grid or a typo'd generator must be a usage error, not a
    # vacuous green sweep / raw KeyError
    if not seeds:
        print(f"error: empty seed grid {args.search_seeds!r}", file=sys.stderr)
        return 2
    unknown = [g for g in generators if g not in GENERATORS]
    if not generators or unknown:
        print(
            f"error: unknown generators {unknown or args.generators!r}; "
            f"available: {sorted(GENERATORS)}",
            file=sys.stderr,
        )
        return 2
    t0 = time.monotonic()
    res = search_schedules(
        seeds,
        generators=generators,
        n_nodes=args.nodes,
        n_validators=args.validators or None,
        height=args.height,
        max_virtual_s=args.max_virtual_s,
        max_wall_s=_wall_budget(args, 120.0),
        shrink=not args.no_shrink,
        scenario_dir=args.scenario_dir or None,
        stop_on_failure=not args.keep_searching,
        progress=(lambda m: print(f"# {m}", file=sys.stderr))
        if args.verbose
        else None,
    )
    out = res.to_dict()
    out["wall_total_s"] = round(time.monotonic() - t0, 3)
    out["seeds"] = seeds
    out["generators"] = generators
    if args.devcheck:
        _attach_devcheck(out)
    print(json.dumps(out, indent=2, default=str))
    return 0 if out.get("ok", res.ok) else 1


def run_scenario(args) -> int:
    """Replay a recorded regression scenario. Exit 0 when it passes,
    1 on a real failure (the bug is back), 3 when the wall budget cut
    the run short — inconclusive, the same classification the search
    applies (machine speed must never read as a regression)."""
    from tendermint_tpu.simnet.search import load_scenario, run_schedule

    kw = load_scenario(args.scenario)
    t0 = time.monotonic()
    rep = run_schedule(
        kw["faults"],
        kw["seed"],
        kw["n_nodes"],
        kw["n_validators"],
        kw["link"],
        kw["height"],
        max_virtual_s=args.max_virtual_s,
        max_wall_s=_wall_budget(args, 120.0),
    )
    inconclusive = (not rep.ok) and rep.wall_budget_hit
    out = rep.to_dict()
    out["scenario"] = args.scenario
    out["inconclusive"] = inconclusive
    out["wall_total_s"] = round(time.monotonic() - t0, 3)
    if args.devcheck:
        _attach_devcheck(out)
    print(json.dumps(out, indent=2, default=str))
    if args.devcheck and out["devcheck"]["violations"]:
        # a recorded checker violation is conclusive evidence regardless
        # of whether the wall budget cut the run short — never exit 3
        return 1
    if not rep.ok and inconclusive:
        return 3
    return 0 if out["ok"] else 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument(
        "--validators",
        type=int,
        default=0,
        help="genesis validator count (0 = all nodes); the rest are "
        "standby full nodes that val_join faults can rotate in",
    )
    ap.add_argument("--height", type=int, default=20)
    ap.add_argument("--max-virtual-s", type=float, default=600.0)
    ap.add_argument(
        "--max-wall-s", type=float, default=-1.0,
        help="bound REAL elapsed time per run (0 = unbounded; default: "
        "unbounded for plain runs, 120s per run in --search/--scenario "
        "modes, where a budget-cut run counts as inconclusive, not a bug)",
    )
    ap.add_argument("--faults", default="", help="JSON fault schedule file")
    ap.add_argument(
        "--preset",
        choices=["none", "partition_heal", "crash_restart", "smoke", "rotation"],
        default="none",
    )
    ap.add_argument(
        "--rotate-every", type=int, default=5,
        help="rotation preset: churn the valset every N heights",
    )
    ap.add_argument(
        "--rotate-start", type=int, default=3,
        help="rotation preset: first churn height",
    )
    ap.add_argument("--txs", type=int, default=0, help="seed N txs per node")
    ap.add_argument("--latency-ms", type=float, default=5.0)
    ap.add_argument("--jitter-ms", type=float, default=0.0)
    ap.add_argument("--drop", type=float, default=0.0)
    ap.add_argument("--duplicate", type=float, default=0.0)
    ap.add_argument("--reorder", type=float, default=0.0)
    ap.add_argument("--bandwidth-bps", type=float, default=0.0)
    ap.add_argument("--trace", default="", help="write Chrome-trace spans here")
    ap.add_argument(
        "--repeat",
        type=int,
        default=1,
        help="run N times with the same seed and require identical fingerprints",
    )
    ap.add_argument(
        "--smoke",
        action="store_true",
        help=f"tier-1 smoke: 4 nodes, smoke schedule, seed {SMOKE_SEED}, "
        f"height {SMOKE_HEIGHT}, two replay-exact runs",
    )
    # -- property-based schedule search ----------------------------------
    ap.add_argument(
        "--search",
        action="store_true",
        help="explore --search-seeds x --generators until an invariant "
        "breaks, then shrink the failing schedule to a minimal repro",
    )
    ap.add_argument(
        "--search-seeds", default="0:10",
        help='seed grid: "a:b" range or comma list (default 0:10)',
    )
    ap.add_argument(
        "--generators", default="mixed,churn",
        help="comma list of schedule generators (mixed, churn)",
    )
    ap.add_argument(
        "--vote-ingress", action="store_true",
        help="attach the stepped live-vote ingress accumulator on every "
             "node (ISSUE 15) — flush points ride the pump, so runs stay "
             "replay-exact",
    )
    ap.add_argument("--no-shrink", action="store_true")
    ap.add_argument(
        "--keep-searching", action="store_true",
        help="do not stop at the first failure",
    )
    ap.add_argument(
        "--scenario-dir", default="",
        help="write the shrunk failing schedule here as a JSON scenario",
    )
    ap.add_argument(
        "--scenario", default="",
        help="replay a recorded regression scenario file and exit",
    )
    ap.add_argument(
        "--inject-bug",
        choices=["", "catchup", "starve"],
        default="",
        help="re-introduce a known-fixed gossip bug (TM_TPU_GOSSIP_BUG_* "
        "seam) so the search demonstrably rediscovers and shrinks it; "
        "'starve' arms the reserved-ingress-slot seam "
        "(TM_TPU_INJECT_LINTBUG, implies devcheck) so a --soak run "
        "demonstrably fails its ingress-admission SLO",
    )
    # -- soak harness (ISSUE 16) ------------------------------------------
    ap.add_argument(
        "--soak", type=float, default=0.0,
        help="run the soak harness for this many VIRTUAL seconds instead "
        "of --height: all four QoS workloads (consensus + light fleets + "
        "tx floods through partition/heal + crash-rejoin catch-up) on one "
        "shared verify engine, with time-series telemetry and per-lane "
        "SLO budgets; --repeat N asserts replay-exact fingerprints",
    )
    ap.add_argument(
        "--soak-rtt-ms", type=float, default=4.0,
        help="soak mocked-relay round-trip per launch (default 4)",
    )
    ap.add_argument(
        "--soak-real", action="store_true",
        help="soak with live kernels instead of the mocked relay",
    )
    ap.add_argument(
        "--soak-out", default="",
        help="write the full soak artifact JSON (gauge rings, windows, "
        "flight recorder on failure) here — tools/soak_report.py renders it",
    )
    # -- shared verification fleet (ISSUE 18) -----------------------------
    ap.add_argument(
        "--fleet", action="store_true",
        help="run the shared-verification-fleet scenario instead of "
        "--height: --fleet-nodes nodes submit EntryBlock verify requests "
        "at all three QoS tiers through the real fleet wire codec to one "
        "fleet host; the verdict asserts zero stalled requests, verdict "
        "parity vs an all-local run, and (--repeat N) replay exactness",
    )
    ap.add_argument(
        "--fleet-nodes", type=int, default=100,
        help="cluster size for --fleet (default 100)",
    )
    ap.add_argument(
        "--fleet-kill-at", type=float, default=4.0,
        help="kill the fleet host this many virtual seconds in "
        "(default 4.0; negative = never)",
    )
    ap.add_argument(
        "--fleet-revive-at", type=float, default=7.0,
        help="revive the fleet host at this virtual second "
        "(default 7.0; negative = never)",
    )
    # -- chain-replay catch-up (ISSUE 14) ---------------------------------
    ap.add_argument(
        "--replay-node", type=int, default=-1,
        help="attach a CatchupDriver to this node index: after it crashes "
        "(schedule a crash fault via --faults/--preset), replay the gap "
        "live through the blocksync ReplayEngine and rejoin at the tip; "
        "the verdict's `catchup` list carries the range hit-rate",
    )
    ap.add_argument(
        "--replay-at", type=int, default=0,
        help="hold the first replay fetch until the live tip reaches this "
        "height, so the rejoin happens N heights behind (0 = chase "
        "immediately)",
    )
    ap.add_argument(
        "--replay-drop", type=float, default=-1.0,
        help="P(range-fetch response lost) on the replay request path "
        "(default: --drop)",
    )
    ap.add_argument(
        "--devcheck",
        action="store_true",
        help="run with the TM_TPU_DEVCHECK runtime invariant checkers on "
        "(relay-thread assertions, lock-order cycle detection, write-"
        "after-resolve canary); the verdict embeds the devcheck report "
        "and any violation fails the run",
    )
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()

    if args.devcheck:
        # before any tendermint_tpu import: import-time lock creation
        # (metrics registries, epoch cache) is then instrumented too
        os.environ["TM_TPU_DEVCHECK"] = "1"

    if args.inject_bug == "catchup":
        # must land before tendermint_tpu.consensus.peer_state is imported
        os.environ["TM_TPU_GOSSIP_BUG_CATCHUP"] = "1"
    if args.inject_bug == "starve":
        # the seam is devcheck-gated (a stale env export with the
        # checkers off must stay inert), so arming it arms devcheck too
        os.environ["TM_TPU_DEVCHECK"] = "1"
        os.environ["TM_TPU_INJECT_LINTBUG"] = "starve"

    if args.scenario:
        return run_scenario(args)
    if args.search:
        return run_search(args)
    if args.soak > 0:
        return run_soak(args)
    if args.fleet:
        return run_fleet(args)

    if args.smoke:
        args.nodes = 4
        args.validators = 0
        args.seed = SMOKE_SEED
        args.height = max(args.height if args.height != 20 else 0, SMOKE_HEIGHT)
        args.preset = "smoke"
        args.repeat = max(args.repeat, 2)

    from tendermint_tpu.observability import trace as _trace

    if args.trace:
        _trace.configure(enabled=True)

    t0 = time.monotonic()
    faults = load_faults(args)
    runs = []
    merged_doc = None
    for i in range(max(args.repeat, 1)):
        out, doc = run_once(
            args, load_faults(args),
            want_trace=bool(args.trace) and i == 0,
        )
        runs.append(out)
        if doc is not None:
            merged_doc = doc
    verdict = dict(runs[0])
    verdict["runs"] = len(runs)
    verdict["wall_total_s"] = round(time.monotonic() - t0, 3)
    verdict["replay_exact"] = all(
        r["fingerprint"] == runs[0]["fingerprint"]
        and r["schedule_digest"] == runs[0]["schedule_digest"]
        for r in runs
    )
    if len(runs) > 1 and not verdict["replay_exact"]:
        verdict["ok"] = False
        verdict["reason"] = "same-seed runs diverged (replay exactness broken)"
    verdict["faults"] = [f.kind for f in faults]
    if args.devcheck:
        _attach_devcheck(verdict)

    if args.trace and merged_doc is not None:
        verdict["trace_path"] = _trace.dump_doc(merged_doc, args.trace)

    print(json.dumps(verdict, indent=2, default=str))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
