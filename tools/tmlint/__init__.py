"""tmlint — repo-specific static analysis for tendermint-tpu (ISSUE 8).

The codebase runs on invariants that generic linters cannot see: exactly
one dispatch-owner thread may touch the relay (ops/pipeline.py), futures
must resolve to host-OWNED verdict memory (the PR-7 donation-aliasing bug
class), simnet must stay replay-exact (no wall clock / global RNG /
unordered-set scheduling in simnet/ and consensus/), the columnar hot
path must stay columnar, and locks follow a fixed discipline. tmlint
turns each of those hard-won bug classes into a mechanical AST pass so it
can never regress silently.

Usage:
    python -m tools.tmlint [paths...] [--json] [--baseline FILE]
    python -m tools.tmlint --write-baseline      # refresh LINT_BASELINE.json

Suppression:
    x = np.asarray(dev)   # tmlint: disable=donation-aliasing — <why>
A comment-only line suppresses the NEXT line too; a suppression on a
`def` line covers the whole function body. `# tmlint: fallback` on a
`def` line is shorthand for disable=hot-path-purity (a documented
object-path / pure-python fallback block). `# tmlint: disable-file=<rule>`
anywhere suppresses the rule for the whole file.

Baseline: grandfathered findings live in LINT_BASELINE.json (fingerprints
are line-number independent, keyed on rule + path + source text), so the
tree gates on NEW findings only. The tier-1 test asserts the gate.

Adding a pass: subclass `core.Rule`, implement `visit(ctx)` yielding
`core.Finding`s, and register it in `rules.ALL_RULES`. Fixture tests in
tests/test_tmlint.py take a positive, a negative, a suppressed, and a
baselined snippet per rule.
"""

from .core import (  # noqa: F401
    Finding,
    Rule,
    fingerprint_findings,
    load_baseline,
    run_paths,
    run_source,
    write_baseline,
)
from .rules import ALL_RULES  # noqa: F401

__all__ = [
    "ALL_RULES",
    "Finding",
    "Rule",
    "fingerprint_findings",
    "load_baseline",
    "run_paths",
    "run_source",
    "write_baseline",
]
