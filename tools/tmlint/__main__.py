"""tmlint CLI.

    python -m tools.tmlint [paths...] [--json] [--baseline FILE]
                           [--write-baseline] [--rules r1,r2] [--list-rules]

Exit-code contract (the tier-1 gate and CI key on this):
    0  no non-baselined findings
    1  at least one new (non-baselined) finding
    2  usage or internal error (unknown rule, unreadable baseline, ...)

Default scan root is the repo root (parent of tools/); default paths are
the tendermint_tpu/ tree; the default baseline is LINT_BASELINE.json at
the repo root when it exists. `--no-baseline` gates on everything.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import ALL_RULES, run_paths
from .core import apply_baseline, load_baseline, write_baseline
from .rules import RULES_BY_NAME

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_PATHS = ["tendermint_tpu"]
DEFAULT_BASELINE = "LINT_BASELINE.json"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.tmlint",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: tendermint_tpu/)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE} at "
                         f"the repo root when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline; gate on every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings as the new baseline "
                         "and exit 0")
    ap.add_argument("--rules", default="",
                    help="comma list of rule names to run (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--root", default=REPO_ROOT, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.name:22s} {r.description}")
        return 0

    if args.write_baseline and (
        args.rules or (args.paths and list(args.paths) != DEFAULT_PATHS)
    ):
        # a baseline written from a rule/path SUBSET would silently drop
        # every other rule's grandfathered fingerprints — the next full
        # run then fails on findings that were supposed to be baselined
        print("error: --write-baseline requires a full run (no --rules, "
              "no path subset) so the baseline stays complete",
              file=sys.stderr)
        return 2

    rules = ALL_RULES
    if args.rules:
        try:
            rules = [RULES_BY_NAME[n.strip()]
                     for n in args.rules.split(",") if n.strip()]
        except KeyError as e:
            print(f"error: unknown rule {e.args[0]!r}; available: "
                  f"{sorted(RULES_BY_NAME)}", file=sys.stderr)
            return 2
        if not rules:
            print("error: --rules selected nothing", file=sys.stderr)
            return 2

    paths = args.paths or DEFAULT_PATHS
    for p in paths:
        ap_ = p if os.path.isabs(p) else os.path.join(args.root, p)
        if not os.path.exists(ap_):
            print(f"error: no such path {p!r}", file=sys.stderr)
            return 2

    try:
        findings = run_paths(paths, args.root, rules)
    except Exception as e:  # noqa: BLE001 — internal errors are exit 2
        print(f"error: lint run failed: {e!r}", file=sys.stderr)
        return 2

    baseline_path = os.path.join(
        args.root, args.baseline or DEFAULT_BASELINE
    ) if not os.path.isabs(args.baseline or "") else args.baseline

    if args.write_baseline:
        data = write_baseline(baseline_path, findings)
        print(f"wrote {len(data['fingerprints'])} fingerprint(s) to "
              f"{baseline_path}")
        return 0

    baseline = set()
    if not args.no_baseline:
        if args.baseline is not None and not os.path.exists(baseline_path):
            print(f"error: baseline {args.baseline!r} not found",
                  file=sys.stderr)
            return 2
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, OSError) as e:
            print(f"error: unreadable baseline {baseline_path!r}: {e}",
                  file=sys.stderr)
            return 2

    new, grandfathered = apply_baseline(findings, baseline)

    if args.as_json:
        print(json.dumps({
            "new": [f.to_dict() for f in new],
            "grandfathered": [f.to_dict() for f in grandfathered],
            "rules": [r.name for r in rules],
            "ok": not new,
        }, indent=2))
    else:
        for f in new:
            print(f"{f.path}:{f.line}:{f.col}: [{f.rule}] {f.message}")
            if f.source_line:
                print(f"    {f.source_line}")
        tail = (f"{len(new)} finding(s)"
                + (f", {len(grandfathered)} baselined" if grandfathered
                   else ""))
        print(("FAIL: " if new else "OK: ") + tail)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
