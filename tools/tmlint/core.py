"""tmlint core: findings, suppression comments, baselines, the runner.

Pure stdlib (ast + tokenize + json) — importable and runnable without
jax, numpy, or the package under analysis, so the lint gate rides the
fast tier-1 path and works in any container.
"""

from __future__ import annotations

import ast
import io
import json
import os
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# findings


class Finding:
    """One rule violation at one source location.

    `fingerprint` is line-number INDEPENDENT (rule + path + the stripped
    source text of the flagged line + occurrence index among identical
    lines) so a committed baseline survives unrelated edits above the
    finding."""

    __slots__ = ("rule", "path", "line", "col", "message", "source_line")

    def __init__(self, rule: str, path: str, line: int, col: int,
                 message: str, source_line: str = ""):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.source_line = source_line.strip()

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "source_line": self.source_line,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


def fingerprint_findings(findings: Sequence[Finding]) -> List[str]:
    """Stable fingerprints, one per finding (order-preserving). Identical
    (rule, path, source text) findings disambiguate by occurrence index
    in file order."""
    seen: Dict[Tuple[str, str, str], int] = {}
    out = []
    for f in findings:
        key = (f.rule, f.path, f.source_line)
        i = seen.get(key, 0)
        seen[key] = i + 1
        out.append(f"{f.rule}:{f.path}:{i}:{f.source_line}")
    return out


# ---------------------------------------------------------------------------
# suppression comments

_MARK = "tmlint:"


class Suppressions:
    """Parsed `# tmlint:` comments for one file.

    - `# tmlint: disable=rule1,rule2` on a code line suppresses those
      rules for that line; on a comment-only line, for the next line too.
    - a suppression landing on a `def`/`class` line covers the whole
      definition span (computed by the runner from the AST).
    - `# tmlint: fallback` is shorthand for disable=hot-path-purity.
    - `# tmlint: disable-file=rule` suppresses the rule file-wide.
    """

    def __init__(self) -> None:
        self.by_line: Dict[int, set] = {}
        self.file_wide: set = set()
        self.spans: List[Tuple[int, int, set]] = []  # (lo, hi, rules)

    @staticmethod
    def _parse_comment(text: str) -> Tuple[Optional[str], set]:
        """-> (kind, rules) where kind is 'line'/'file'/None."""
        body = text.lstrip("#").strip()
        if not body.startswith(_MARK):
            return None, set()
        body = body[len(_MARK):].strip()
        # allow a trailing justification after an em/en dash or ';'
        for sep in ("—", "–", ";", " -- "):
            if sep in body:
                body = body.split(sep, 1)[0].strip()
        if body.startswith("disable-file="):
            rules = body[len("disable-file="):]
            return "file", {r.strip() for r in rules.split(",") if r.strip()}
        if body.startswith("disable="):
            rules = body[len("disable="):]
            # "disable=all" is spelled literally and matches every rule
            return "line", {r.strip() for r in rules.split(",") if r.strip()}
        if body.split()[0:1] == ["fallback"]:
            return "line", {"hot-path-purity"}
        return None, set()

    @classmethod
    def scan(cls, source: str) -> "Suppressions":
        sup = cls()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                kind, rules = cls._parse_comment(tok.string)
                if not rules:
                    continue
                if kind == "file":
                    sup.file_wide |= rules
                    continue
                line = tok.start[0]
                sup.by_line.setdefault(line, set()).update(rules)
                # comment-only line: applies to the following line as well
                prefix = tok.line[: tok.start[1]]
                if prefix.strip() == "":
                    sup.by_line.setdefault(line + 1, set()).update(rules)
        except tokenize.TokenError:
            pass
        return sup

    def add_span(self, lo: int, hi: int, rules: set) -> None:
        self.spans.append((lo, hi, rules))

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_wide or "all" in self.file_wide:
            return True
        rules = self.by_line.get(line)
        if rules and (rule in rules or "all" in rules):
            return True
        for lo, hi, rs in self.spans:
            if lo <= line <= hi and (rule in rs or "all" in rs):
                return True
        return False


# ---------------------------------------------------------------------------
# rule base + file context


class FileContext:
    """Everything a rule needs for one file: the AST, raw lines, the
    repo-relative path, and the parsed suppressions."""

    def __init__(self, relpath: str, source: str, tree: ast.AST,
                 suppressions: Suppressions):
        self.path = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.suppressions = suppressions

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule, self.path, line, col, message,
                       self.line_text(line))


class Rule:
    """A lint pass. Subclasses set `name`, `description`, and an optional
    `scope` (path-prefix / filename filter) and implement visit()."""

    name = "rule"
    description = ""

    def applies_to(self, relpath: str) -> bool:
        return True

    def visit(self, ctx: FileContext) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError


# ---------------------------------------------------------------------------
# runner


def _function_spans(tree: ast.AST) -> List[Tuple[int, int]]:
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            end = getattr(node, "end_lineno", node.lineno)
            spans.append((node.lineno, end))
    return spans


def _promote_def_suppressions(tree: ast.AST, sup: Suppressions) -> None:
    """A suppression on (or immediately above) a def/class line covers the
    whole definition body."""
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            continue
        rules = set(sup.by_line.get(node.lineno, ()))
        if rules:
            end = getattr(node, "end_lineno", node.lineno)
            sup.add_span(node.lineno, end, rules)


def run_source(source: str, relpath: str,
               rules: Sequence[Rule]) -> List[Finding]:
    """Lint one in-memory file. Unparsable sources yield a single
    `parse-error` finding rather than crashing the run."""
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError as e:
        return [Finding("parse-error", relpath, e.lineno or 1, 0,
                        f"could not parse: {e.msg}")]
    sup = Suppressions.scan(source)
    _promote_def_suppressions(tree, sup)
    ctx = FileContext(relpath, source, tree, sup)
    out: List[Finding] = []
    seen = set()  # rules that scan per-function revisit nested defs
    for rule in rules:
        if not rule.applies_to(relpath):
            continue
        for f in rule.visit(ctx):
            key = (f.rule, f.line, f.col)
            if key in seen or sup.suppressed(f.rule, f.line):
                continue
            seen.add(key)
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def iter_py_files(paths: Sequence[str], root: str) -> Iterator[Tuple[str, str]]:
    """-> (abspath, root-relative path with forward slashes)."""
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            yield ap, os.path.relpath(ap, root).replace(os.sep, "/")
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__"
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    full = os.path.join(dirpath, fn)
                    yield full, os.path.relpath(full, root).replace(os.sep, "/")


def run_paths(paths: Sequence[str], root: str,
              rules: Sequence[Rule]) -> List[Finding]:
    out: List[Finding] = []
    for ap, rel in iter_py_files(paths, root):
        with open(ap, "r", encoding="utf-8") as fh:
            src = fh.read()
        out.extend(run_source(src, rel, rules))
    return out


# ---------------------------------------------------------------------------
# baseline


def load_baseline(path: str) -> set:
    """-> the set of grandfathered fingerprints (empty for a missing
    file, so a fresh checkout gates on everything)."""
    if not os.path.exists(path):
        return set()
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return set(data.get("fingerprints", []))


def write_baseline(path: str, findings: Sequence[Finding]) -> dict:
    data = {
        "comment": (
            "tmlint grandfathered findings. Entries here are pre-existing "
            "audit items, not approvals — shrink this file, never grow it. "
            "Regenerate with `python -m tools.tmlint --write-baseline`."
        ),
        "fingerprints": fingerprint_findings(findings),
        "findings": [f.to_dict() for f in findings],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return data


def apply_baseline(findings: Sequence[Finding],
                   baseline: set) -> Tuple[List[Finding], List[Finding]]:
    """-> (new, grandfathered) split by fingerprint."""
    new: List[Finding] = []
    old: List[Finding] = []
    for f, fp in zip(findings, fingerprint_findings(findings)):
        (old if fp in baseline else new).append(f)
    return new, old
