"""donation-aliasing — non-owning verdict memory escaping ops/ functions.

The PR-7 incident: futures were resolved with `np.asarray(device_result)`
— on the CPU backend a ZERO-COPY view of the XLA output buffer. With
buffer donation on, a later launch recycles that page and mutates
verdicts already delivered to callers (a [0,1,...] verdict row flipped to
all-ones after resolution). The fix discipline: anything that ESCAPES a
function (return / Future.set_result / accumulator .append) must be
host-OWNED memory — `np.array(x)`, `x.copy()`, `.astype(...)`, or a
concatenate — never a bare `np.asarray(...)` or a slice of one.

Intra-procedural, flow-insensitive: a name is tainted if it is ever
assigned a non-owning producer and NEVER assigned an owning one (so the
`if not arr.flags.owndata: arr = np.array(arr, copy=True)` guard pattern
clears the taint). Slices of tainted names stay tainted.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ..core import FileContext, Finding, Rule
from . import func_name, iter_functions

_OWNING_CALLS = {
    "array", "copy", "astype", "concatenate", "stack", "empty", "zeros",
    "ones", "full", "frombuffer", "fromiter", "repeat", "tolist",
}
_ESCAPE_SETTERS = {"set_result"}
_ACCUMULATORS = {"append", "extend"}


def _is_asarray(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call) and func_name(node) == "asarray")


def _is_owning_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and func_name(node) in _OWNING_CALLS


class _FnScan(ast.NodeVisitor):
    """One function body: collect tainted/owned names, then flag escapes."""

    def __init__(self, ctx: FileContext, rule_name: str):
        self.ctx = ctx
        self.rule = rule_name
        self.tainted: Set[str] = set()
        self.owned: Set[str] = set()
        self.findings = []

    # -- taint collection (first pass) -----------------------------------

    def _value_taints_vs(self, v: ast.AST, tainted: Set[str]) -> bool:
        if _is_asarray(v):
            return True
        if isinstance(v, ast.Subscript):
            return self._value_taints_vs(v.value, tainted)
        if isinstance(v, ast.IfExp):
            return (self._value_taints_vs(v.body, tainted)
                    or self._value_taints_vs(v.orelse, tainted))
        if isinstance(v, ast.Name):
            return v.id in tainted
        return False

    @staticmethod
    def _bindings(node: ast.AST):
        """(name, value) pairs from every assignment form: plain Assign
        (incl. element-wise tuple targets), AnnAssign (`res: T = ...` —
        an annotation must not launder taint), and walrus NamedExpr."""
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    yield tgt.id, node.value
                elif (isinstance(tgt, ast.Tuple)
                      and isinstance(node.value, ast.Tuple)
                      and len(tgt.elts) == len(node.value.elts)):
                    for t, v in zip(tgt.elts, node.value.elts):
                        if isinstance(t, ast.Name):
                            yield t.id, v
        elif (isinstance(node, ast.AnnAssign) and node.value is not None
              and isinstance(node.target, ast.Name)):
            yield node.target.id, node.value
        elif (isinstance(node, ast.NamedExpr)
              and isinstance(node.target, ast.Name)):
            yield node.target.id, node.value

    def collect(self, fn: ast.AST) -> None:
        """Fold bindings in SOURCE order, last binding per name wins: the
        owndata-guard (`arr = np.array(arr, copy=True)` after the
        asarray) clears the taint because it comes later, while an
        owned init OVERWRITTEN by a device view (`out = np.zeros(n);
        out = np.asarray(dev)[:n]`) stays tainted — order-insensitive
        ever-owned-wins let that exact PR-7 shape through. Branches fold
        by source position (known flow-insensitivity; the guard idiom
        puts the owning reassign last). Two sweeps so `b = a[:n]` sees
        a's final taint regardless of binding interleavings; unknown
        producers clear taint (true reassignment)."""
        binds = sorted(
            ((getattr(n, "lineno", 0), getattr(n, "col_offset", 0), nm, v)
             for n in ast.walk(fn) for nm, v in self._bindings(n)),
            key=lambda t: (t[0], t[1]),
        )
        for _ in range(2):
            tainted: Set[str] = set()
            owned: Set[str] = set()
            for _, _, name, value in binds:
                if _is_owning_call(value):
                    owned.add(name)
                    tainted.discard(name)
                elif self._value_taints_vs(value, self.tainted | tainted):
                    tainted.add(name)
                    owned.discard(name)
                else:
                    # unknown producer: a real reassignment — the old
                    # binding (tainted or owned) is gone
                    tainted.discard(name)
                    owned.discard(name)
            self.tainted, self.owned = tainted, owned

    # -- escape checks (second pass) -------------------------------------

    def _expr_escapes(self, v: ast.AST) -> bool:
        """Is this expression non-owning memory (directly or via taint)?"""
        if _is_asarray(v):
            return True
        if isinstance(v, ast.Name):
            return v.id in self.tainted
        if isinstance(v, ast.Subscript):
            return self._expr_escapes(v.value)
        if isinstance(v, ast.IfExp):
            return self._expr_escapes(v.body) or self._expr_escapes(v.orelse)
        return False

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(self.ctx.finding(
            self.rule, node,
            f"{what} escapes with non-owning array memory (zero-copy view "
            f"of a device/XLA buffer; a donated later launch can mutate it "
            f"after delivery) — wrap in np.array(...)/.copy()",
        ))

    def check(self, fn: ast.AST) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                vals = (node.value.elts
                        if isinstance(node.value, ast.Tuple)
                        else [node.value])
                for v in vals:
                    if self._expr_escapes(v):
                        self._flag(node, "return value")
                        break
            elif isinstance(node, ast.Call):
                name = func_name(node)
                if name in _ESCAPE_SETTERS:
                    for a in node.args:
                        if self._expr_escapes(a):
                            self._flag(node, "Future.set_result argument")
                            break
                elif name in _ACCUMULATORS:
                    for a in node.args:
                        # bare asarray(x) append is common and benign
                        # (e.g. collecting already-owned future results);
                        # the bug shape is a SLICE of a device result or
                        # a tainted name accumulated across launches
                        if (isinstance(a, ast.Subscript)
                                and self._expr_escapes(a)) or (
                                isinstance(a, ast.Name)
                                and a.id in self.tainted):
                            self._flag(node, "accumulator argument")
                            break


class DonationAliasingRule(Rule):
    name = "donation-aliasing"
    description = (
        "non-owning device-result views (np.asarray / slices of it) must "
        "not escape ops/ functions — the PR-7 write-after-resolve bug class"
    )

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("tendermint_tpu/ops/")

    def visit(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in iter_functions(ctx.tree):
            scan = _FnScan(ctx, self.name)
            scan.collect(fn)
            scan.check(fn)
            yield from scan.findings
