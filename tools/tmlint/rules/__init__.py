"""tmlint rule registry + shared AST helpers.

Each pass lives in its own module and encodes ONE invariant the repo has
already paid for in a real bug or a hard design rule (see each module's
docstring for the incident it guards). Register new passes in ALL_RULES.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Rule  # noqa: F401  (re-export for subclass authors)


def func_name(call: ast.Call) -> str:
    """Terminal callee name: `a.b.c(...)` -> 'c', `f(...)` -> 'f'."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def receiver_name(call: ast.Call) -> str:
    """Immediate receiver of an attribute call: `a.b.c(...)` -> 'b',
    `np.asarray(...)` -> 'np', plain `f(...)` -> ''."""
    f = call.func
    if isinstance(f, ast.Attribute):
        v = f.value
        if isinstance(v, ast.Attribute):
            return v.attr
        if isinstance(v, ast.Name):
            return v.id
    return ""


def dotted(node: ast.AST) -> str:
    """Best-effort dotted path of a Name/Attribute chain ('' otherwise)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def iter_functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


from .determinism import SimnetDeterminismRule  # noqa: E402
from .fleet import FleetTransportRule  # noqa: E402
from .ingress import IngressDisciplineRule  # noqa: E402
from .donation import DonationAliasingRule  # noqa: E402
from .locks import LockDisciplineRule  # noqa: E402
from .purity import HotPathPurityRule  # noqa: E402
from .relay import RelayOwnershipRule  # noqa: E402

ALL_RULES = [
    DonationAliasingRule(),
    IngressDisciplineRule(),
    RelayOwnershipRule(),
    FleetTransportRule(),
    SimnetDeterminismRule(),
    HotPathPurityRule(),
    LockDisciplineRule(),
]

RULES_BY_NAME = {r.name: r for r in ALL_RULES}
