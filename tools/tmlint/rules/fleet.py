"""fleet-transport — fleet wire entry points outside the fleet modules.

ISSUE 18: the verification fleet's wire format (length-prefixed
columnar EntryBlock frames) has exactly three sanctioned homes —
fleet/wire.py (the codec itself), fleet/client.py, and fleet/server.py
(the two endpoints, including their socket-free loopback doubles). The
frame layout is a versioned compatibility surface: a fourth module
encoding frames by hand, or calling the codec directly to smuggle
blocks over its own socket, forks the protocol — version negotiation,
the oversize/malformed containment contract, metrics attribution, and
the flow-continuation discipline all silently stop holding. Same shape
as relay-ownership: route through fleet.client.FleetClient (or
LoopbackSession) instead.

Only the fleet codec's OWN entry-point names are flagged — generic
socket calls (sendall et al.) stay legal everywhere because rpc/,
privval/, and p2p/ legitimately own their sockets.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Rule
from . import func_name

# modules allowed to touch the wire codec (repo-relative)
WHITELIST = frozenset({
    "tendermint_tpu/fleet/wire.py",    # the codec
    "tendermint_tpu/fleet/client.py",  # node-side endpoint + LoopbackSession
    "tendermint_tpu/fleet/server.py",  # fleet-side endpoint + LoopbackFleetHost
})

# the codec's entry points (terminal callee names)
ENTRY_POINTS = frozenset({
    "encode_submit",
    "encode_verdicts",
    "encode_error",
    "parse_frame",
    "send_frame",
    "iter_frames",
    "FrameDecoder",
})


class FleetTransportRule(Rule):
    name = "fleet-transport"
    description = (
        "fleet wire-codec call sites are only legal inside fleet/wire.py, "
        "fleet/client.py, and fleet/server.py"
    )

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith("tendermint_tpu/")
                and relpath not in WHITELIST)

    def visit(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = func_name(node)
            if name in ENTRY_POINTS:
                yield ctx.finding(
                    self.name, node,
                    f"fleet wire entry point `{name}()` called outside the "
                    f"fleet transport modules — the frame format is a "
                    f"versioned compatibility surface; go through "
                    f"fleet.client.FleetClient (or LoopbackSession) instead",
                )
