"""ingress-discipline — hand-rolled windowed accumulators outside the fabric.

ISSUE 17 collapsed four parallel copies of the same machinery — mempool
ingress, vote ingress, light verify and blocksync replay each owned a
window dict, a flush-timer thread and its own EntryBlock assembly — into
ONE engine (`ops/ingress.py`): one flush scheduler, one completion
thread, one poisoned-window / fallback / QoS policy. A fifth parallel
stack must never grow back: every new batched-verify consumer registers
a LaneSpec with the shared engine instead of spawning its own flusher.

The tell for a hand-rolled accumulator is the PAIR of signals in one
module, neither of which is suspicious alone:

  1. a flush/window timer thread — `threading.Thread(target=<something
     named *flush*/*window*/*timer*/*drain*>)`, and
  2. EntryBlock assembly for submission — `EntryBlock.from_entries(...)`
     (or `.concat`).

Plenty of modules legitimately build EntryBlocks (benches, the replay
prep path) and plenty spawn threads (the pipeline, the soak harness);
only the combination re-creates a private batching engine. The engine
itself is the single whitelisted module.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from ..core import FileContext, Finding, Rule
from . import dotted, func_name, receiver_name

# the one module architecturally sanctioned to own window/flush machinery
WHITELIST = frozenset({
    "tendermint_tpu/ops/ingress.py",
})

# substrings that mark a thread target as a window-flush loop
_FLUSH_HINTS = ("flush", "window", "timer", "drain")

# EntryBlock assembly entry points (terminal callee names)
_ASSEMBLY = frozenset({"from_entries", "concat"})


def _target_name(call: ast.Call) -> str:
    """Dotted name of the `target=` keyword of a Thread(...) call."""
    for kw in call.keywords:
        if kw.arg == "target":
            return dotted(kw.value)
    if call.args:  # Thread(group, target, ...) positional form
        if len(call.args) >= 2:
            return dotted(call.args[1])
    return ""


class IngressDisciplineRule(Rule):
    name = "ingress-discipline"
    description = ("windowed accumulator (flush thread + EntryBlock "
                   "assembly) outside ops/ingress.py")

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith("tendermint_tpu/")
                and relpath not in WHITELIST)

    def visit(self, ctx: FileContext) -> Iterator[Finding]:
        flush_threads: List[ast.Call] = []
        assembles = False
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = func_name(node)
            if name == "Thread":
                tgt = _target_name(node).lower()
                if tgt and any(h in tgt for h in _FLUSH_HINTS):
                    flush_threads.append(node)
            elif name in _ASSEMBLY and receiver_name(node) == "EntryBlock":
                assembles = True
        if not assembles:
            return
        for call in flush_threads:
            yield ctx.finding(
                self.name, call,
                "flush-timer thread + EntryBlock assembly in one module "
                "re-creates a private batching engine; register a LaneSpec "
                "with ops.ingress.shared_engine() instead")
