"""relay-ownership — device-touching entry points outside the dispatcher.

PERF_r05 §2: the TPU relay is ONE serial command channel. Transfers
neither overlap execution nor tolerate concurrency, so exactly one thread
— the pipeline's dispatch-owner — may launch kernels, issue device_put
transfers, or upload epoch tables. The module whitelist below is the full
set of modules architecturally sanctioned to hold relay-touching code
(the dispatcher itself, the transfer/table implementations, the kernel
definitions, and the direct-path fallbacks in ops/backend.py). A call to
any launch/transfer entry point from ANY other module is a structural
violation: route it through ops.pipeline.AsyncBatchVerifier instead.

The runtime half of this invariant is libs/devcheck.py's relay-thread
assertion (TM_TPU_DEVCHECK=1); this pass catches the call SITES the
runtime hooks would only catch when exercised.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Rule
from . import func_name, receiver_name

# modules allowed to contain relay-touching calls (repo-relative)
WHITELIST = frozenset({
    "tendermint_tpu/ops/pipeline.py",      # the dispatch-owner thread
    "tendermint_tpu/ops/device_pool.py",   # transfer() implementation
    "tendermint_tpu/ops/epoch_cache.py",   # lazy table upload (dispatcher-run)
    "tendermint_tpu/ops/backend.py",       # sanctioned direct path + warmup
    "tendermint_tpu/ops/ed25519_verify.py",
    "tendermint_tpu/ops/pallas_verify.py",
    "tendermint_tpu/ops/pallas_rlc.py",
    "tendermint_tpu/ops/pallas_sr25519.py",
    "tendermint_tpu/ops/sharded.py",
    "tendermint_tpu/ops/mesh.py",          # mesh-dispatcher packing + prep
    "tendermint_tpu/ops/mixed.py",
    "tendermint_tpu/ops/bls_verify.py",    # BLS pairing kernel definitions
    "tendermint_tpu/ops/_testing.py",      # test scaffolding, not production
})

# launch / transfer / upload entry points (terminal callee names)
ENTRY_POINTS = frozenset({
    "device_put",
    "copy_to_host_async",
    "block_until_ready",
    "jitted_verify",
    "jitted_verify_device_hash",
    "cached_kernel",
    "rlc_cached_fn",
    "cached_compact_fn",
    "_jitted_rlc_verify",
    "_jitted_pallas_verify",
    "verify_kernel_cached",
    "xla_tables",
    "coords_tables",
    # mesh dispatcher (ISSUE 9): superbatch launch builders + the
    # replicated epoch-table uploads
    "mesh_valid_fn",
    "mesh_valid_fn_cached",
    "mesh_pallas_valid_fn",
    "epoch_tables_sharded",
    "sharded_xla_tables",
    "prepare_superbatch",
    # BLS aggregation lane (ISSUE 20): the fused multi-pairing launch
    # builders and the direct code-row path — aggregated commits must
    # reach the device through AsyncBatchVerifier / the mesh, never by
    # jitting the pairing kernels at the call site
    "jitted_bls_verify",
    "jitted_bls_finalexp",
    "bls_kernel",
    "verify_batch_bls_codes",
    # mocked-relay device doubles (ISSUE 11): these REPLACE the relay for
    # benches/gates — production code (the light service's dispatch path
    # included) must route through AsyncBatchVerifier, never wire a mock
    "mock_light_prepare",
    "mock_mesh_prepare",
    "mock_mempool_prepare",
    "mock_vote_prepare",
    "slow_prepare",
    "slow_mesh_prepare",
})

# `transfer` is a common word; only flag it on a device_pool-ish receiver
_QUALIFIED = {"transfer": ("_dpool", "device_pool", "dpool", "pool")}


class RelayOwnershipRule(Rule):
    name = "relay-ownership"
    description = (
        "kernel-launch / device_put / epoch-table-upload call sites are "
        "only legal inside the dispatcher module whitelist"
    )

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith("tendermint_tpu/")
                and relpath not in WHITELIST)

    def visit(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = func_name(node)
            hit = name in ENTRY_POINTS
            if not hit and name in _QUALIFIED:
                hit = receiver_name(node) in _QUALIFIED[name]
            if hit:
                yield ctx.finding(
                    self.name, node,
                    f"relay entry point `{name}()` called outside the "
                    f"dispatcher whitelist — only the single dispatch-owner "
                    f"thread (ops/pipeline.py) may touch the device; submit "
                    f"through AsyncBatchVerifier instead",
                )
