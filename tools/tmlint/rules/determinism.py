"""simnet-determinism — wall clocks / global RNG / unordered iteration.

simnet's whole value is replay exactness: same seed ⇒ byte-identical run
fingerprint (PR 3), which is what makes a failing fault-schedule a repro
and lets the property-based search shrink schedules (PR 6). That breaks
the moment any simnet-reachable code path reads the wall clock
(`time.time`, `datetime.now`), draws from the process-global RNG
(`random.random()` — as opposed to a seeded `random.Random(seed)`
instance), reads OS entropy (`os.urandom`, `uuid.uuid4`, `secrets`), or
lets a Python `set`'s hash-order feed a scheduling decision.

Scope: tendermint_tpu/simnet/, tendermint_tpu/consensus/ (the modules
the simnet harness drives), tendermint_tpu/light/ (ISSUE 11:
simnet-driven light clients and the batched verification service — their
wall-clock default lives in libs/timeutil and rides in via the `now_fn`
seams, so the light modules themselves lint clean without suppressions)
and tendermint_tpu/blocksync/ (ISSUE 14: the simnet rejoin scenario
drives the replay engine and BlockPool; the pool's wall-clock default
rides in via its injected `clock` seam).
The injection seams are the allowlist: clocks ride `self._now` / injected
`clock` objects, randomness rides seeded `random.Random` instances —
neither matches these patterns, so correctly injected code lints clean by
construction.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ..core import FileContext, Finding, Rule
from . import func_name, iter_functions, receiver_name

_TIME_RECEIVERS = {"time", "_time"}
_TIME_FNS = {"time", "time_ns"}
_DATETIME_RECEIVERS = {"datetime", "date"}
_DATETIME_FNS = {"now", "utcnow", "today"}
_ENTROPY = {
    ("os", "urandom"),
    ("uuid", "uuid1"),
    ("uuid", "uuid4"),
}


class SimnetDeterminismRule(Rule):
    name = "simnet-determinism"
    description = (
        "no wall clock, global RNG, OS entropy, or unordered-set iteration "
        "in simnet-reachable code — replay exactness depends on it"
    )

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(
            ("tendermint_tpu/simnet/", "tendermint_tpu/consensus/",
             "tendermint_tpu/light/", "tendermint_tpu/blocksync/")
        )

    # -- call patterns ---------------------------------------------------

    def _bad_call(self, node: ast.Call) -> str:
        name = func_name(node)
        recv = receiver_name(node)
        if recv in _TIME_RECEIVERS and name in _TIME_FNS:
            return (f"wall-clock read `{recv}.{name}()` — use the injected "
                    f"clock (self._now / SimClock) so replays stay exact")
        if recv in _DATETIME_RECEIVERS and name in _DATETIME_FNS:
            return (f"wall-clock read `{recv}.{name}()` — derive timestamps "
                    f"from the injected clock")
        if (recv, name) in _ENTROPY or recv == "secrets":
            return (f"OS entropy `{recv}.{name}()` — draw from the seeded "
                    f"run RNG instead")
        if recv == "random":
            # the MODULE-level (process-global) RNG; a seeded
            # random.Random(seed) instance is the sanctioned pattern
            if name == "Random":
                if not node.args and not node.keywords:
                    return ("unseeded random.Random() — pass an explicit "
                            "seed so the run replays")
                return ""
            return (f"process-global RNG `random.{name}()` — use a seeded "
                    f"random.Random instance threaded from the run seed")
        return ""

    # -- set iteration ---------------------------------------------------

    @staticmethod
    def _set_names(fn: ast.AST) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, (ast.Set, ast.SetComp)
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
            elif (isinstance(node, ast.Assign)
                  and isinstance(node.value, ast.Call)
                  and func_name(node.value) == "set"):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        return names

    def _is_set_expr(self, node: ast.AST, set_names: Set[str]) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call) and func_name(node) == "set":
            return True
        if isinstance(node, ast.Name):
            return node.id in set_names
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub)
        ):
            return (self._is_set_expr(node.left, set_names)
                    or self._is_set_expr(node.right, set_names))
        return False

    def visit(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                msg = self._bad_call(node)
                if msg:
                    yield ctx.finding(self.name, node, msg)
        # unordered iteration: a `for` (or comprehension) directly over a
        # set expression — hash order feeds whatever the loop decides.
        # `sorted(set(...))` / `list(sorted(...))` wrappers are fine and
        # do not match (the iterable is the sorted() call).
        for fn in iter_functions(ctx.tree):
            set_names = self._set_names(fn)
            for node in ast.walk(fn):
                iters = []
                if isinstance(node, ast.For):
                    iters.append(node.iter)
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.GeneratorExp, ast.DictComp)):
                    iters.extend(g.iter for g in node.generators)
                for it in iters:
                    if self._is_set_expr(it, set_names):
                        yield ctx.finding(
                            self.name, node,
                            "iteration over an unordered set — hash order "
                            "varies across processes and feeds scheduling; "
                            "iterate a list/dict (insertion-ordered) or "
                            "wrap in sorted()",
                        )
                        break
