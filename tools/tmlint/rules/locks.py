"""lock-discipline — bare acquisitions and unauditable thread targets.

Two shapes this repo has been burned by:

1. Bare `lock.acquire()` as a statement. A `with lock:` block releases on
   every exit path; a bare acquire leaks the lock on any exception
   between acquire and release (the PR-1 metrics self-deadlock was this
   family). Semaphores are exempt — the pipeline's depth semaphore is
   deliberately acquired and released on DIFFERENT threads (dispatcher /
   resolver), which a context manager cannot express; receivers with
   "sem" in the name do not match. Cross-method Lock/Unlock APIs that
   mirror the Go reference (mempool.Mempool.Lock) carry an explicit
   suppression with justification.

2. `threading.Thread(target=...)` where the target is a lambda (nothing
   to audit) or, outside the relay whitelist, a same-module function
   whose body calls relay entry points — a thread that would touch the
   device without being the dispatch-owner. The runtime twin of this
   check is devcheck's relay-thread assertion.

3. `fut.result()` under a mutex (ISSUE 13): a `.result()` call inside a
   `with <...mtx...>:` block parks the lock across a device round-trip.
   If the thread that completes that future ever needs the same lock
   (the ingress completer finishing CheckTx needs the mempool's `_mtx`),
   that's a deadlock, and even when it isn't, every other lock client
   stalls for a full relay RTT. Scoped to receivers whose name contains
   "mtx" — the repo's convention for state mutexes — so coordination
   locks built FOR result-collection (pipeline.py's `done_lock`) don't
   false-positive. Wait on futures outside the lock, or hand completion
   to a dedicated thread (mempool/ingress.py's completer).

4. Dispatch `submit()` under a mutex (ISSUE 15): submitting to the
   shared verifier can BLOCK on the pipeline's depth semaphore when the
   device queue is full, so a `<verifier>.submit(...)` inside a
   `with <...mtx...>:` block parks the state mutex across the
   dispatcher's backpressure — and the verdict callback that would
   relieve it usually needs that same lock (the vote accumulator's
   window mutex, the mempool's `_mtx`). The vote-ingress submit path is
   the reference shape: stage under `_mtx`, pop the window, release,
   THEN submit (consensus/vote_ingress.py's `_flush_window`). Scoped to
   verifier-ish receivers ("verifier"/"ingress" in the name, the `_v`
   handle convention, or a `shared_verifier()`/`_ensure_verifier()`
   chain) so executor pools (`prep_pool.submit`) stay out of scope.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator

from ..core import FileContext, Finding, Rule
from . import func_name, receiver_name
from .relay import ENTRY_POINTS, WHITELIST


def _terminal_receiver(call: ast.Call) -> str:
    """self._mtx.acquire() -> '_mtx' (the attr nearest the call)."""
    if isinstance(call.func, ast.Attribute):
        inner = call.func.value
        if isinstance(inner, ast.Attribute):
            return inner.attr
        if isinstance(inner, ast.Name):
            return inner.id
    return ""


def _ctx_name(expr: ast.AST) -> str:
    """`with self._mtx:` / `with mtx:` -> the lock's terminal name."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


# shape-4 scoping: which `.submit()` receivers count as a pipeline
# dispatch (vs. an executor pool, which is non-blocking bookkeeping)
_DISPATCH_RECEIVER_SUBSTR = ("verifier", "ingress")
_DISPATCH_RECEIVER_EXACT = ("_v", "v")
_DISPATCH_CHAIN_CALLS = ("shared_verifier", "_ensure_verifier")


def _is_dispatch_submit(call: ast.Call) -> bool:
    """`<verifier-ish>.submit(...)` — including the repo's
    `self._ensure_verifier().submit(...)` / `shared_verifier().submit(...)`
    lazy-handle chains, whose immediate receiver is a Call, not a Name."""
    if func_name(call) != "submit":
        return False
    recv = receiver_name(call)
    if recv:
        low = recv.lower()
        return (any(s in low for s in _DISPATCH_RECEIVER_SUBSTR)
                or recv in _DISPATCH_RECEIVER_EXACT)
    if isinstance(call.func, ast.Attribute) and isinstance(
            call.func.value, ast.Call):
        return func_name(call.func.value) in _DISPATCH_CHAIN_CALLS
    return False


def _walk_same_frame(nodes) -> Iterator[ast.AST]:
    """Walk statements WITHOUT descending into nested function/lambda
    bodies — code in a `def` inside a `with` block runs later, on some
    other thread's frame, not under this lock."""
    stack = list(nodes)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = (
        "locks are acquired via context managers (semaphores exempt); "
        "thread targets must be auditable and relay-clean"
    )

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("tendermint_tpu/")

    # -- helpers ---------------------------------------------------------

    @staticmethod
    def _local_functions(tree: ast.AST) -> Dict[str, ast.AST]:
        fns: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns.setdefault(node.name, node)
        return fns

    @staticmethod
    def _touches_relay(fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and func_name(node) in ENTRY_POINTS:
                return True
        return False

    # -- visit -----------------------------------------------------------

    def visit(self, ctx: FileContext) -> Iterator[Finding]:
        local_fns = self._local_functions(ctx.tree)
        whitelisted = ctx.path in WHITELIST
        for node in ast.walk(ctx.tree):
            # 1) bare `x.acquire()` as a statement
            if (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)
                    and func_name(node.value) == "acquire"):
                recv = _terminal_receiver(node.value)
                if "sem" not in recv.lower():
                    yield ctx.finding(
                        self.name, node,
                        f"bare `{recv or '<expr>'}.acquire()` — use "
                        f"`with {recv or 'lock'}:` so every exit path "
                        f"releases (cross-thread handoffs are what "
                        f"semaphores are for)",
                    )
            # 3) `fut.result()` while holding a state mutex
            if isinstance(node, ast.With):
                lock = ""
                for item in node.items:
                    name = _ctx_name(item.context_expr)
                    if "mtx" in name.lower():
                        lock = name
                        break
                if lock:
                    for sub in _walk_same_frame(node.body):
                        if (isinstance(sub, ast.Call)
                                and func_name(sub) == "result"):
                            yield ctx.finding(
                                self.name, sub,
                                f"`.result()` inside `with {lock}:` parks "
                                f"the mutex across a future's round-trip — "
                                f"deadlock bait if the completing thread "
                                f"needs {lock}; wait outside the lock or "
                                f"complete on a dedicated thread",
                            )
                        # 4) dispatch submit while holding the mutex
                        elif (isinstance(sub, ast.Call)
                                and _is_dispatch_submit(sub)):
                            yield ctx.finding(
                                self.name, sub,
                                f"pipeline `submit()` inside `with {lock}:` "
                                f"— submit blocks on the dispatcher's depth "
                                f"semaphore under backpressure, parking "
                                f"{lock} until the device drains; stage "
                                f"under the lock, release, then submit "
                                f"(see consensus/vote_ingress.py "
                                f"_flush_window)",
                            )
            # 2) thread targets
            if isinstance(node, ast.Call) and func_name(node) == "Thread":
                if receiver_name(node) not in ("threading", ""):
                    continue
                target = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
                if target is None:
                    continue
                if isinstance(target, ast.Lambda):
                    yield ctx.finding(
                        self.name, node,
                        "thread target is a lambda — name the function so "
                        "its lock/relay behavior is auditable",
                    )
                elif not whitelisted and isinstance(target, ast.Name):
                    fn = local_fns.get(target.id)
                    if fn is not None and self._touches_relay(fn):
                        yield ctx.finding(
                            self.name, node,
                            f"thread target `{target.id}` calls relay entry "
                            f"points outside the dispatcher whitelist — "
                            f"only ops/pipeline.py's dispatch-owner thread "
                            f"may touch the device",
                        )
