"""hot-path-purity — per-signature Python loops in the columnar modules.

PRs 2/4 moved the commit-verify hot path to columnar-from-decode: one
GIL-released fused call (or grouped numpy) per BATCH, never per
signature. The three modules below are the columnar core; a `for` loop
that walks signatures one Python iteration at a time (or grows a list
with per-element .append) re-introduces exactly the per-tuple cost those
PRs removed — at 10k signatures that is the difference between ~0.3 ms
and ~15 ms of GIL-held host time per commit (PERF_r06).

What counts as per-element (and gets flagged):
  - `for i in range(len(x))` / `range(n)` / `range(self.n)` / `range(x.n)`
  - `for ... in enumerate(...)`
  - `for ... in entries` / `...iter_entries()` / `...to_entries()`

Grouped loops (over np.unique lengths, flag groups, blocks of jobs) are
the DESIGN — a handful of iterations regardless of batch size — and do
not match. Sanctioned object-path fallbacks are marked `# tmlint:
fallback` on the def line (shorthand for disable=hot-path-purity over the
function body); new fallbacks must be marked the same way.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Rule
from . import func_name

MODULES = frozenset({
    "tendermint_tpu/ops/entry_block.py",
    "tendermint_tpu/ops/commit_prep.py",
    "tendermint_tpu/wire/canonical.py",
})

_ENTRY_NAMES = {"entries"}
_ENTRY_CALLS = {"iter_entries", "to_entries", "enumerate"}
_N_NAMES = {"n"}


def _is_per_element_iter(it: ast.AST) -> bool:
    if isinstance(it, ast.Call):
        name = func_name(it)
        if name in _ENTRY_CALLS:
            return True
        if name == "range" and len(it.args) == 1:
            a = it.args[0]
            if isinstance(a, ast.Call) and func_name(a) == "len":
                return True
            if isinstance(a, ast.Name) and a.id in _N_NAMES:
                return True
            if isinstance(a, ast.Attribute) and a.attr in _N_NAMES:
                return True
        return False
    if isinstance(it, ast.Name) and it.id in _ENTRY_NAMES:
        return True
    return False


class HotPathPurityRule(Rule):
    name = "hot-path-purity"
    description = (
        "no per-signature Python for-loops / per-element appends in the "
        "columnar hot-path modules outside fallback-marked blocks"
    )

    def applies_to(self, relpath: str) -> bool:
        return relpath in MODULES

    def visit(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.For):
                continue
            if _is_per_element_iter(node.iter):
                yield ctx.finding(
                    self.name, node,
                    "per-element Python loop in a columnar hot-path module "
                    "— vectorize (grouped numpy / fused native call) or "
                    "mark the block `# tmlint: fallback` if it is a "
                    "documented object-path fallback",
                )
