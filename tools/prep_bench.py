#!/usr/bin/env python3
"""Host prep microbenchmark: tuple-list vs columnar EntryBlock commit prep.

Measures the `commit_entries -> prepare_batch` path — the GIL-held host
work between types.verify_commit and the device kernel that PERF_r05
identified as the binding constraint (~40 ms/commit against ~23 ms of
device time at 8 concurrent commits) — for both representations:

  baseline   per-signature (pub32, msg, sig64) tuples: vote_sign_bytes_many
             (one PyBytes per lane), a tuple per signature, b"".join
             re-copies inside prepare_batch (the pre-EntryBlock shape)
  columnar   pipeline.commit_entries -> EntryBlock (one contiguous
             sign-bytes buffer + offset table, (n,32)/(n,64) columns) ->
             prepare_batch consuming the block directly

Runs on the CPU backend with NO device work (prep only). By default the
native module is DISABLED (TM_TPU_NO_NATIVE=1) so the numbers isolate the
representation change itself — the pure-Python fallback path, which is
also the acceptance gate (ISSUE 2: >= 2x). Pass --native to keep the
native module and measure the fused-call path instead.

Usage:
    JAX_PLATFORMS=cpu python tools/prep_bench.py [--sigs 10000] [--reps 5]
                                                 [--native]
"""

import argparse
import os
import statistics
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TM_TPU_PUREPY_CRYPTO", "1")

if "--native" not in sys.argv:
    os.environ["TM_TPU_NO_NATIVE"] = "1"

FUSED_SPEEDUP_GATE = 1.3  # --fused: decode->kernel-args vs the PR-4 path
TRANSFER_RATIO_GATE = 0.5  # --transfer: warm-epoch H2D vs cold-epoch H2D
TRANSFER_SPEEDUP_GATE = 1.3  # --transfer: cached prep vs the PR-4 prep
OVERLAP_POOL_DEPTH = 2  # --overlap: double-buffered input slots

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build_synthetic_commit(n_sigs: int):
    """A 10k-scale commit with structurally-valid random signatures.

    Prep cost does not depend on signature VALIDITY (the same hashes,
    packs and transposes run either way), so the bench skips n_sigs
    actual signing ops (~3 ms each under the pure-Python fallback)."""
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.types.block import (
        BLOCK_ID_FLAG_COMMIT,
        BlockID,
        Commit,
        CommitSig,
        PartSetHeader,
    )
    from tendermint_tpu.types.validator_set import Validator, ValidatorSet
    from tendermint_tpu.wire.canonical import Timestamp

    rng = np.random.RandomState(1234)
    vals = []
    sigs = []
    for i in range(n_sigs):
        pk = ed25519.PubKey(rng.randint(0, 256, 32, dtype=np.uint8).tobytes())
        vals.append(Validator.new(pk, 100))
        sigs.append(
            CommitSig(
                block_id_flag=BLOCK_ID_FLAG_COMMIT,
                validator_address=pk.address(),
                # distinct nanos per lane: a real commit's timestamps
                # differ, so the sign-bytes composer gets no free cache
                # hits here
                timestamp=Timestamp(seconds=1_700_000_000, nanos=int(i) + 1),
                signature=rng.randint(0, 256, 64, dtype=np.uint8).tobytes(),
            )
        )
    # keep commit.signatures index-aligned with the validator list: build
    # the set WITHOUT the power-sort by address (ValidatorSet.new sorts)
    vset = ValidatorSet(validators=vals, proposer=vals[0])
    block_id = BlockID(
        hash=b"\x11" * 32, part_set_header=PartSetHeader(total=1, hash=b"\x22" * 32)
    )
    commit = Commit(height=42, round=0, block_id=block_id, signatures=sigs)
    return vset, commit


def commit_entries_tuples(chain_id, vals, commit, voting_power_needed):
    """The pre-EntryBlock commit_entries, kept verbatim as the baseline:
    per-lane PyBytes sign-bytes + one Python tuple per signature."""
    idxs = []
    tallied = 0
    for idx, cs in enumerate(commit.signatures):
        if not cs.for_block():
            continue
        idxs.append(idx)
        tallied += vals.validators[idx].voting_power
        if tallied > voting_power_needed:
            break
    sign_bytes = commit.vote_sign_bytes_many(chain_id, idxs)
    return [
        (vals.validators[i].pub_key.bytes(), sb, commit.signatures[i].signature)
        for i, sb in zip(idxs, sign_bytes)
    ]


def run_fused(args) -> int:
    """--fused: the round-6 columnar-from-decode gate. Measures the full
    decode-to-kernel-args path — wire-decoded commit (CommitBlock
    columns) -> fused prep (ops/commit_prep.py) -> device-hash kernel
    args — against the PR-2 columnar path (commit_entries_legacy object
    walk + generic pad), enforces bit-identical kernel args, and gates
    the speedup at >= FUSED_SPEEDUP_GATE on CPU."""
    import statistics as stats

    from tendermint_tpu.native import load as _load_native
    from tendermint_tpu.ops import backend, pipeline
    from tendermint_tpu.types.block import Commit

    chain_id = "prep-bench"
    vset, commit = build_synthetic_commit(args.sigs)
    needed = vset.total_voting_power() * 2 // 3
    bucket = backend._bucket_for(args.sigs)
    native = _load_native()
    dec = Commit.decode(commit.encode())
    if dec.commit_block() is None:
        print("  FAIL: decode did not produce a CommitBlock", file=sys.stderr)
        return 2
    print(
        f"prep_bench --fused: n={args.sigs} bucket={bucket} reps={args.reps} "
        f"native={'yes' if native is not None else 'no'} "
        f"backend={os.environ.get('JAX_PLATFORMS', '?')}"
    )

    def fused():
        dec._sb_tpl = None
        blk, _ = pipeline.commit_entries(chain_id, vset, dec, needed)
        return backend.prepare_batch_device_hash(blk, bucket)

    def pr2():
        commit._sb_tpl = None
        blk, _ = pipeline.commit_entries_legacy(
            chain_id, vset, commit, needed
        )
        return backend.prepare_batch_device_hash(blk, bucket)

    # interleave reps so machine noise hits both paths equally
    fused()
    pr2()
    t_f, t_p = [], []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        fused()
        t_f.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        pr2()
        t_p.append(time.perf_counter() - t0)
    f_ms = stats.median(t_f) * 1e3
    p_ms = stats.median(t_p) * 1e3
    speedup = p_ms / f_ms if f_ms else float("inf")
    a_f = fused()
    a_p = pr2()
    parity = all(np.array_equal(x, y) for x, y in zip(a_f, a_p))
    print(f"  PR-2 columnar (decode->args): {p_ms:9.2f} ms")
    print(f"  fused columnar-from-decode  : {f_ms:9.2f} ms")
    print(f"  speedup                     : {speedup:9.2f}x")
    print(f"  arg parity                  : {'OK' if parity else 'MISMATCH'}")
    if not parity:
        return 2
    if speedup < FUSED_SPEEDUP_GATE:
        print(
            f"  FAIL: expected >= {FUSED_SPEEDUP_GATE}x decode->kernel-args "
            "speedup",
            file=sys.stderr,
        )
        return 1
    return 0


def run_transfer(args) -> int:
    """--transfer: the round-7 epoch-cache gate. A validator set seen for
    the SECOND time is device-resident (ops/epoch_cache.py), so a warm
    commit ships only per-signature data — this gate asserts, on both the
    device-hash and host-hash XLA preps:

      bytes    steady-state (warm) H2D bytes <= TRANSFER_RATIO_GATE x the
               cold-epoch bytes (the uncached batch args PLUS the one-time
               epoch table upload)
      no pubs  the warm host-hash args carry NO pubkey-derived arrays —
               exactly gather indices + raw r/s/k rows + s<L flags
      speed    warm host prep >= TRANSFER_SPEEDUP_GATE x faster than the
               PR-4 prep of the same batch (interleaved min-of-reps)
    """
    import statistics as stats

    os.environ.setdefault("TM_TPU_EPOCH_CACHE", "8")
    from tendermint_tpu.ops import backend, epoch_cache, pipeline
    from tendermint_tpu.types.block import Commit

    chain_id = "prep-bench"
    vset, commit = build_synthetic_commit(args.sigs)
    needed = vset.total_voting_power() * 2 // 3
    bucket = backend._bucket_for(args.sigs)
    dec = Commit.decode(commit.encode())
    epoch_cache.reset()
    if epoch_cache.cache() is None:
        print("  FAIL: epoch cache disabled (TM_TPU_EPOCH_CACHE=0?)",
              file=sys.stderr)
        return 2
    # first sight: cold epoch — the commit rides the uncached path while
    # the table registers
    blk_cold, _ = pipeline.commit_entries(chain_id, vset, dec, needed)
    if blk_cold.epoch_key is not None:
        print("  FAIL: first-sight commit unexpectedly warm", file=sys.stderr)
        return 2
    blk, _ = pipeline.commit_entries(chain_id, vset, dec, needed)
    ep = epoch_cache.lookup(blk)
    if ep is None:
        print("  FAIL: second-sight commit not warm", file=sys.stderr)
        return 2
    print(
        f"prep_bench --transfer: n={args.sigs} bucket={bucket} "
        f"reps={args.reps} vp={ep.vp} "
        f"backend={os.environ.get('JAX_PLATFORMS', '?')}"
    )

    rc = 0
    table_b = ep.nbytes_host()
    for name, uncached, cached in (
        (
            "device-hash",
            lambda b=blk_cold: backend.prepare_batch_device_hash(b, bucket),
            lambda: backend.prepare_batch_cached_device_hash(blk, bucket, ep),
        ),
        (
            "host-hash",
            lambda b=blk_cold: backend.prepare_batch(b, bucket),
            lambda: backend.prepare_batch_cached(blk, bucket, ep),
        ),
    ):
        cold_b = backend.h2d_arg_bytes(uncached()) + table_b
        warm_args = cached()
        warm_b = backend.h2d_arg_bytes(warm_args)
        ratio = warm_b / cold_b
        # interleaved min-of-reps (this box's allocator noise drifts
        # medians +-30%; see tests/test_gil_budget.py)
        uncached(); cached()
        t_u, t_c = [], []
        for _ in range(args.reps):
            t0 = time.perf_counter(); uncached()
            t_u.append(time.perf_counter() - t0)
            t0 = time.perf_counter(); cached()
            t_c.append(time.perf_counter() - t0)
        u_ms, c_ms = min(t_u) * 1e3, min(t_c) * 1e3
        speedup = u_ms / c_ms if c_ms else float("inf")
        print(f"  {name}:")
        print(f"    cold-epoch H2D (args+table): {cold_b:>10} B")
        print(f"    warm-epoch H2D (args only) : {warm_b:>10} B")
        print(f"    warm/cold ratio            : {ratio:10.3f}")
        print(f"    PR-4 prep                  : {u_ms:8.2f} ms")
        print(f"    cached prep                : {c_ms:8.2f} ms")
        print(f"    speedup                    : {speedup:8.2f}x")
        if ratio > TRANSFER_RATIO_GATE:
            print(
                f"  FAIL: warm H2D > {TRANSFER_RATIO_GATE}x cold on {name}",
                file=sys.stderr,
            )
            rc = 1
        if speedup < TRANSFER_SPEEDUP_GATE:
            print(
                f"  FAIL: cached prep < {TRANSFER_SPEEDUP_GATE}x faster "
                f"on {name}",
                file=sys.stderr,
            )
            rc = 1
    # structural "no pubkey bytes": the warm host-hash args are exactly
    # idx(4) + r(32) + s(32) + k(32) bytes per lane + the s<L flags
    idx, r_rows, s_rows, k_rows, s_ok = backend.prepare_batch_cached(
        blk, bucket, ep
    )
    expected = bucket * (4 + 32 + 32 + 32) + s_ok.nbytes
    got = backend.h2d_arg_bytes((idx, r_rows, s_rows, k_rows, s_ok))
    if got != expected:
        print(
            f"  FAIL: warm host-hash args ship {got} B, expected {expected} "
            "(pubkey-derived array leaked into the warm path?)",
            file=sys.stderr,
        )
        rc = 2
    else:
        print(f"  warm host-hash args structurally pub-free: {got} B")
    return rc


def run_overlap(args) -> int:
    """--overlap: the round-8 overlapped-relay gate, an on-CPU proxy for
    the transfer/compute pipelining ISSUE 7 adds to the dispatcher.

    The device is mocked SLOW on the readback side only (a proxy result
    whose materialization sleeps ~150 ms — the resolver blocks exactly
    like a relay-attached TPU's D2H wait), so the dispatcher's loop
    structure is what decides whether batch k+1's H2D transfer is issued
    while batch k computes. Asserts, over a stream of single-job batches
    at depth 1:

      split    every batch's `pipeline.transfer` span closes before its
               `pipeline.dispatch` span opens (transfer split from launch)
      overlap  transfer k+1 is issued BEFORE batch k resolves (span-order
               check transfer[k+1].start < device_wait[k].end, and the
               dispatcher's own hidden=1 marking agrees) — the serial
               prep->transfer->launch->wait loop this PR removed fails
               this deterministically
      pool     steady-state allocations are FLAT: the buffer pool mints
               at most OVERLAP_POOL_DEPTH slots for the whole stream
               (misses == depth, every later acquire is a recycled hit)
               and leaks nothing (in_flight == 0 once drained)
      owner    transfers and launches all ran on ONE thread (the relay
               single-owner invariant extends to the transfer stage)
    """
    import numpy as np

    from tendermint_tpu.observability import trace as tr
    from tendermint_tpu.ops import backend, pipeline as pl

    n = 96
    n_batches = 6
    resolve_delay = 0.15

    rng = np.random.RandomState(7)

    def batch(tag: int):
        # structurally-valid random entries: the overlap timing being
        # gated does not depend on signature validity
        return [
            (
                rng.randint(0, 256, 32, dtype=np.uint8).tobytes(),
                b"overlap-%d-%d" % (tag, i),
                rng.randint(0, 256, 64, dtype=np.uint8).tobytes(),
            )
            for i in range(n)
        ]

    # one submitted job == one device batch (the coalescer would fuse
    # the whole stream into a single launch otherwise); the slow-readback
    # mock is shared with tests/test_overlap.py (ops/_testing.py)
    from tendermint_tpu.ops._testing import drain_pool, slow_prepare

    backend.max_coalesce = lambda: n
    pl.AsyncBatchVerifier._prepare = staticmethod(
        slow_prepare(pl.AsyncBatchVerifier._prepare, resolve_delay)
    )

    tr.TRACER.clear()
    tr.configure(enabled=True)
    v = pl.AsyncBatchVerifier(depth=1, pool_depth=OVERLAP_POOL_DEPTH)
    try:
        v.submit(batch(99)).result(timeout=600)  # warm: compile the shape
        futs = [v.submit(batch(t)) for t in range(n_batches)]
        for f in futs:
            f.result(timeout=600)
        # the resolver completes futures BEFORE releasing the slot —
        # drain so the leak check does not race the last release
        drain_pool(v._pool)
        pool = v._pool.stats()
    finally:
        tr.configure(enabled=False)
        v.close()

    evs = {"pipeline.transfer": [], "pipeline.dispatch": [],
           "pipeline.device_wait": []}
    tids = set()
    for name, start, end, tid, sargs in tr.TRACER.events():
        if name in evs:
            evs[name].append((start, end, sargs or {}))
        if name in ("pipeline.transfer", "pipeline.dispatch"):
            tids.add(tid)
    for k in evs:
        evs[k].sort()
    xfers = evs["pipeline.transfer"][1:]        # drop the warmup batch
    dispatches = evs["pipeline.dispatch"][1:]
    waits = evs["pipeline.device_wait"][1:]

    print(
        f"prep_bench --overlap: n={n} batches={n_batches} depth=1 "
        f"pool_depth={OVERLAP_POOL_DEPTH} resolve_delay={resolve_delay}s"
    )
    rc = 0
    if not (len(xfers) == len(dispatches) == len(waits) == n_batches):
        print(
            f"  FAIL: expected {n_batches} transfer/dispatch/wait span "
            f"triples, got {len(xfers)}/{len(dispatches)}/{len(waits)}",
            file=sys.stderr,
        )
        return 2
    split_ok = all(x[1] <= d[0] for x, d in zip(xfers, dispatches))
    overlapped = sum(
        1 for i in range(1, n_batches) if xfers[i][0] < waits[i - 1][1]
    )
    hidden = sum(1 for x in xfers if x[2].get("hidden"))
    print(f"  transfer-before-launch split : {'OK' if split_ok else 'BROKEN'}")
    print(f"  transfer k+1 < resolve k     : {overlapped}/{n_batches - 1}")
    print(f"  dispatcher-marked hidden     : {hidden}/{n_batches}")
    print(f"  pool                         : {pool}")
    print(f"  transfer+dispatch threads    : {len(tids)}")
    if not split_ok:
        print("  FAIL: a transfer span closed after its launch span opened",
              file=sys.stderr)
        rc = 1
    if overlapped < n_batches - 2:
        print(
            f"  FAIL: only {overlapped}/{n_batches - 1} transfers were "
            "issued before the previous batch resolved (dispatcher is "
            "serial again?)",
            file=sys.stderr,
        )
        rc = 1
    if hidden < n_batches - 1:
        print(
            f"  FAIL: dispatcher marked only {hidden}/{n_batches} "
            "transfers hidden behind in-flight compute",
            file=sys.stderr,
        )
        rc = 1
    if pool["minted"] > OVERLAP_POOL_DEPTH:
        print(
            f"  FAIL: pool minted {pool['minted']} slots for one layout "
            f"(> depth {OVERLAP_POOL_DEPTH}) — steady-state allocations "
            "are not flat",
            file=sys.stderr,
        )
        rc = 1
    if pool["in_flight"] != 0:
        print(f"  FAIL: {pool['in_flight']} pool slots leaked",
              file=sys.stderr)
        rc = 1
    if len(tids) != 1:
        print(
            f"  FAIL: transfers/launches ran on {len(tids)} threads "
            "(single relay owner violated)",
            file=sys.stderr,
        )
        rc = 1
    return rc


def run_mesh(args) -> int:
    """--mesh: the round-9 mesh-dispatcher gate, on a mocked 2-lane mesh
    (this box has one device; lane packing + demux is exactly the
    machinery that must be right WITHOUT mesh hardware). The kernel runs
    for real — verdicts are live — behind a slow-readback mock so the
    overlap stages engage like a relay-attached mesh. Asserts:

      pack     deterministic plan shapes: 3 full jobs over a 4-lane plan
               leave one PURE identity-padding lane; per-lane single-
               epoch packing holds; spans tile the live rows exactly
      parity   every job's mesh-packed verdict row is bit-identical to
               the single-device path's (backend.verify_batch), and the
               blame index (first invalid lane) of a tampered job
               survives the demux
      pool     zero slot leak once drained (in_flight == 0)
      owner    transfers and launches all ran on ONE thread — the relay
               single-owner invariant extends to the mesh superbatch
      overlap  superbatch k+1's transfer is issued before batch k
               resolves (the ISSUE 7 machinery generalized to lane-
               packed launches)
      gauges   mesh_lane_occupancy + mesh_pad_waste_ratio published and
               complementary
    """
    import numpy as np

    from tendermint_tpu.libs import jaxcache
    from tendermint_tpu.libs.metrics import ops_stats

    # persistent kernel cache: the 2-lane superbatch shape compiles once
    # per machine, not once per gate run
    import jax

    jaxcache.enable(jax, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from tendermint_tpu.observability import trace as tr
    from tendermint_tpu.ops import backend, mesh as ms, pipeline as pl
    from tendermint_tpu.ops._testing import drain_pool, slow_mesh_prepare
    from tendermint_tpu.ops.entry_block import EntryBlock

    os.environ["TM_TPU_MESH_LANE_BUCKET"] = "128"
    resolve_delay = 0.15
    rng = np.random.RandomState(11)

    def rand_batch(n, tag):
        """Structurally-valid random entries — pack/plan checks only."""
        return EntryBlock.from_entries([
            (
                rng.randint(0, 256, 32, dtype=np.uint8).tobytes(),
                b"mesh-%d-%d" % (tag, i),
                rng.randint(0, 256, 64, dtype=np.uint8).tobytes(),
            )
            for i in range(n)
        ])

    from tendermint_tpu.crypto import ed25519

    def signed_batch(n, tag, bad=()):
        """REAL signatures (parity and blame must see live verdicts),
        with `bad` lane indices tampered."""
        out = []
        for i in range(n):
            sk = ed25519.gen_priv_key(
                (tag * 1000 + i + 1).to_bytes(32, "little")
            )
            m = b"mesh-%d-%d" % (tag, i)
            sig = sk.sign(m) if i not in bad else b"\x07" * 64
            out.append((sk.pub_key().bytes(), m, sig))
        return EntryBlock.from_entries(out)

    print("prep_bench --mesh: lanes=2 lane_bucket=128 "
          f"resolve_delay={resolve_delay}s")
    rc = 0

    # -- pack determinism (no kernel): pure-pad lane + span tiling ------
    class _J:
        def __init__(self, blk):
            self.entries = blk

    plan, held = ms.pack_jobs(
        [_J(rand_batch(128, 90)), _J(rand_batch(128, 91)),
         _J(rand_batch(128, 92))], 4, 128
    )
    block, spans = ms.build_superblock(plan)
    pure_pad = plan.n_lanes - len(plan.lanes)
    rows = np.zeros(plan.bucket, dtype=bool)
    for _, off, n in spans:
        if rows[off:off + n].any():
            print("  FAIL: demux spans overlap", file=sys.stderr)
            rc = 1
        rows[off:off + n] = True
    pad_rows = block.pub[plan.live:]
    pad_ok = bool(
        (pad_rows[:, 0] == 1).all() and (pad_rows[:, 1:] == 0).all()
    )
    print(f"  plan: lanes={plan.n_lanes} (pure-pad={pure_pad}) "
          f"live={plan.live} pad={plan.pad} span_rows={int(rows.sum())} "
          f"identity_pad={'OK' if pad_ok else 'BROKEN'}")
    if held or pure_pad != 1 or int(rows.sum()) != plan.live or not pad_ok:
        print("  FAIL: 3 full jobs over 4 lanes must pack 3 live lanes + "
              "1 pure identity-pad lane with exact span tiling",
              file=sys.stderr)
        rc = 1

    # -- live pipeline: parity / blame / pool / owner / overlap ---------
    # job 3 carries one tampered lane (row 17) so the demuxed blame
    # index is checkable against live verdicts
    jobs = [
        signed_batch(n, t, bad=(17,) if t == 3 else ())
        for t, n in enumerate((96, 31, 5, 128, 64, 7))
    ]
    pl.AsyncBatchVerifier._prepare_mesh = staticmethod(
        slow_mesh_prepare(pl.AsyncBatchVerifier._prepare_mesh,
                          resolve_delay)
    )
    tr.TRACER.clear()
    tr.configure(enabled=True)
    v = pl.AsyncBatchVerifier(depth=1, pool_depth=OVERLAP_POOL_DEPTH,
                              mesh_lanes=2)
    try:
        v.submit(jobs[0][0:16]).result(timeout=600)  # warm: compile
        futs = [v.submit(j) for j in jobs]
        res = [np.asarray(f.result(timeout=600)) for f in futs]
        drain_pool(v._pool)
        pool = v._pool.stats()
        stats = ops_stats()
    finally:
        tr.configure(enabled=False)
        v.close()

    mism = None
    for i, (j, r) in enumerate(zip(jobs, res)):
        want = backend.verify_batch(j)
        if not np.array_equal(r, np.asarray(want)):
            mism = i
    # live-verdict blame: ONLY job 3's row 17 fails across the pack
    blame_ok = bool(
        not res[3][17] and res[3].sum() == len(res[3]) - 1
        and all(r.all() for i, r in enumerate(res) if i != 3)
    )
    print(f"  verdict parity vs single-device: "
          f"{'OK' if mism is None else f'MISMATCH job {mism}'}")
    print(f"  tampered-lane blame demux       : "
          f"{'OK' if blame_ok else 'LOST'}")
    if mism is not None or not blame_ok:
        rc = 1

    evs = {"pipeline.transfer": [], "pipeline.dispatch": [],
           "pipeline.device_wait": []}
    tids = set()
    for name, start, end, tid, sargs in tr.TRACER.events():
        if name in evs:
            evs[name].append((start, end, sargs or {}))
        if name in ("pipeline.transfer", "pipeline.dispatch"):
            tids.add(tid)
    for k in evs:
        evs[k].sort()
    xfers = evs["pipeline.transfer"]
    waits = evs["pipeline.device_wait"]
    nb = len(xfers)
    overlapped = sum(
        1 for i in range(1, min(nb, len(waits)))
        if xfers[i][0] < waits[i - 1][1]
    )
    print(f"  superbatches launched           : {nb}")
    print(f"  transfer k+1 < resolve k        : {overlapped}/{max(nb-1, 0)}")
    print(f"  transfer+dispatch threads       : {len(tids)}")
    print(f"  pool                            : {pool}")
    print(f"  mesh_lane_occupancy={stats['mesh_lane_occupancy']:.4f} "
          f"mesh_pad_waste_ratio={stats['mesh_pad_waste_ratio']:.4f}")
    if nb < 2:
        print("  FAIL: expected >= 2 superbatch launches", file=sys.stderr)
        rc = 2
    elif overlapped < 1:
        print("  FAIL: no superbatch transfer overlapped the previous "
              "batch's resolve (mesh dispatcher is serial?)",
              file=sys.stderr)
        rc = 1
    if len(tids) != 1:
        print(f"  FAIL: transfers/launches ran on {len(tids)} threads "
              "(single relay owner violated)", file=sys.stderr)
        rc = 1
    if pool["in_flight"] != 0:
        print(f"  FAIL: {pool['in_flight']} pool slots leaked",
              file=sys.stderr)
        rc = 1
    occ = stats["mesh_lane_occupancy"]
    padr = stats["mesh_pad_waste_ratio"]
    if not (0.0 < occ <= 1.0) or abs((occ + padr) - 1.0) > 1e-9:
        print(f"  FAIL: occupancy {occ} + pad waste {padr} must be "
              "complementary and published", file=sys.stderr)
        rc = 1
    return rc


def run_schemes(args) -> int:
    """--schemes: the ISSUE 19 scheme-lane gate. A mixed
    ed25519+secp256k1 committee must verify in ONE superbatch launch
    with verdicts AND blame byte-identical to the sequential reference
    walk. Every kernel runs REAL (live verdicts) — correctness is the
    gate here; throughput is `bench.py schemes` (SCHEMES_r*.json).
    Asserts:

      split    prepare_commit_scheme_split partitions a mixed commit
               into per-scheme EntryBlocks (ed25519 first), covering
               every counted signature exactly once
      pack     the mesh packer takes both blocks into ONE plan whose
               superblock is a SchemeSuperBlock with contiguous
               per-scheme segments in plan.schemes() order
      launch   prepare_superbatch hands back ONE launch fn; a single
               call verifies every lane — one relay command for a
               mixed-scheme commit (the mixed-commit acceptance)
      parity   demuxed per-job verdict rows are bit-identical to the
               single-scheme device path (backend.verify_batch), on
               the direct drive AND through the pipeline mesh worker
      blame    a tampered secp256k1 signature raises from conclude()
               with the EXACT error string of the sequential
               _verify_commit_single walk; same for a tampered
               ed25519 signature
      lanes    the secp device verdict row equals the host
               per-signature loop bit-for-bit, including a
               non-lower-S rejection
    """
    import jax

    from tendermint_tpu.libs import jaxcache

    jaxcache.enable(jax, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    os.environ["TM_TPU_MESH_LANE_BUCKET"] = "16"

    from tendermint_tpu.crypto import ed25519 as _ed
    from tendermint_tpu.crypto import secp256k1 as _secp
    from tendermint_tpu.ops import backend, device_pool as dp, mesh as ms
    from tendermint_tpu.ops import pipeline as pl
    from tendermint_tpu.ops._testing import drain_pool
    from tendermint_tpu.types import (
        BlockID,
        PartSetHeader,
        Timestamp,
        Validator,
        ValidatorSet,
        Vote,
        VoteSet,
    )
    from tendermint_tpu.types.block import CommitSig
    from tendermint_tpu.types.vote import PRECOMMIT_TYPE
    from tendermint_tpu.types import validation as V

    chain_id = "schemes-gate"
    n_vals = 12
    print(f"prep_bench --schemes: vals={n_vals} (mixed ed25519+secp256k1) "
          "lane_bucket=16")
    rc = 0

    def build_commit(tag):
        """A mixed committee (every 3rd validator ed25519, the rest
        secp256k1) with REAL signatures — blame must see live verdicts."""
        pairs = []
        for i in range(n_vals):
            seed = (tag * 4096 + i + 1).to_bytes(32, "big")
            sk = (_ed.gen_priv_key(seed) if i % 3 == 0
                  else _secp.PrivKey(seed))
            pairs.append((sk, Validator.new(sk.pub_key(), 100)))
        vset = ValidatorSet.new([v for _, v in pairs])
        by_addr = {v.address: sk for sk, v in pairs}
        sks = [by_addr[v.address] for v in vset.validators]
        bid = BlockID(hash=b"\x05" * 32,
                      part_set_header=PartSetHeader(total=1, hash=b"\x05" * 32))
        vs = VoteSet(chain_id, 7, 0, PRECOMMIT_TYPE, vset)
        for i, sk in enumerate(sks):
            vote = Vote(type=PRECOMMIT_TYPE, height=7, round=0, block_id=bid,
                        timestamp=Timestamp(seconds=1_600_000_000, nanos=0),
                        validator_address=vset.validators[i].address,
                        validator_index=i)
            sig = sk.sign(vote.sign_bytes(chain_id))
            vs.add_vote(Vote(**{**vote.__dict__, "signature": sig}))
        return vset, vs.make_commit()

    def tamper(commit, i):
        cs = commit.signatures[i]
        bad = bytearray(cs.signature)
        bad[9] ^= 0x3C
        commit.signatures[i] = CommitSig(
            block_id_flag=cs.block_id_flag,
            validator_address=cs.validator_address,
            timestamp=cs.timestamp, signature=bytes(bad))

    def seq_error(vset, commit):
        try:
            V._verify_commit_single(
                chain_id, vset, commit, vset.total_voting_power() * 2 // 3,
                V._ignore_not_for_block, V._count_all, False, True)
            return None
        except ValueError as e:
            return str(e)

    class _J:
        def __init__(self, blk):
            self.entries = blk

    def one_launch(blocks):
        """The acceptance drive: both scheme blocks through the
        PRODUCTION pack/build/prep path, verified by a SINGLE call of
        the one launch fn prepare_superbatch returns."""
        jobs = [_J(b) for b in blocks]
        plan, held = ms.pack_jobs(jobs, len(jobs))
        assert not held, "scheme blocks must pack into one plan"
        block, spans = ms.build_superblock(plan)
        res = ms.prepare_superbatch(block, plan)
        f, fargs = res[0], res[1]
        shardings = res[4] if len(res) > 4 else None
        arr = np.asarray(f(*dp.transfer(fargs, shardings=shardings)))
        if arr.ndim == 2:
            arr = arr[0]
        arr = arr.astype(bool)
        by_job = {id(j): (off, n) for j, off, n in spans}
        outs = []
        for j in jobs:
            off, n = by_job[id(j)]
            outs.append(arr[off:off + n])
        return plan, block, outs

    # -- split + pack + ONE launch + verdict parity (good commit) -------
    vset, commit = build_commit(1)
    blocks, conclude = V.prepare_commit_scheme_split(
        chain_id, vset, commit, vset.total_voting_power() * 2 // 3)
    schemes = [b.scheme for b in blocks]
    covered = sum(len(b) for b in blocks)
    # equal powers: the selection walk stops at the first signature that
    # crosses 2/3 of total power, exactly like _verify_commit_single
    want_rows = (vset.total_voting_power() * 2 // 3) // 100 + 1
    print(f"  split: blocks={schemes} rows={[len(b) for b in blocks]} "
          f"(threshold walk selects {want_rows})")
    if schemes != ["ed25519", "secp256k1"] or covered != want_rows:
        print("  FAIL: mixed commit must split into ed25519+secp256k1 "
              "blocks covering every counted signature exactly once",
              file=sys.stderr)
        rc = 1
    plan, sblock, outs = one_launch(blocks)
    is_super = isinstance(sblock, ms.SchemeSuperBlock)
    parts = [s for s, _, _ in sblock.parts] if is_super else []
    print(f"  pack : superblock={'SchemeSuperBlock' if is_super else type(sblock).__name__} "
          f"parts={parts} schemes={plan.schemes()}")
    if not is_super or parts != plan.schemes():
        print("  FAIL: mixed plan must build a SchemeSuperBlock with "
              "per-scheme segments in plan order", file=sys.stderr)
        rc = 1
    print("  launch: 1 (single fn call covered all "
          f"{plan.bucket} rows, {plan.live} live)")
    mism = None
    for i, (b, got) in enumerate(zip(blocks, outs)):
        want = np.asarray(backend.verify_batch(b))
        if not np.array_equal(got, want):
            mism = i
    print(f"  parity vs single-scheme device  : "
          f"{'OK' if mism is None else f'MISMATCH block {mism}'}")
    if mism is not None:
        rc = 1
    try:
        conclude(np.concatenate(outs))
        print("  good commit verdict             : OK (verified)")
    except ValueError as e:
        print(f"  FAIL: good mixed commit rejected: {e}", file=sys.stderr)
        rc = 1

    # -- blame parity: tampered secp sig, then tampered ed sig ----------
    for label, bad_i in (("secp256k1", 1), ("ed25519", 0)):
        vset, commit = build_commit(2)
        # pick a commit index of the wanted scheme
        kinds, _, _ = vset.scheme_rows()
        want_kind = 1 if label == "secp256k1" else 0
        idx = int(np.nonzero(kinds == want_kind)[0][bad_i])
        tamper(commit, idx)
        want_err = seq_error(vset, commit)
        blocks, conclude = V.prepare_commit_scheme_split(
            chain_id, vset, commit, vset.total_voting_power() * 2 // 3)
        _, _, outs = one_launch(blocks)
        try:
            conclude(np.concatenate(outs))
            got_err = None
        except ValueError as e:
            got_err = str(e)
        ok = want_err is not None and got_err == want_err
        print(f"  blame parity ({label:9s})      : "
              f"{'OK' if ok else 'MISMATCH'}")
        if not ok:
            print(f"  FAIL: sequential={want_err!r} batched={got_err!r}",
                  file=sys.stderr)
            rc = 1

    # -- pipeline mesh worker: same verdicts through the async path -----
    vset, commit = build_commit(1)
    blocks, conclude = V.prepare_commit_scheme_split(
        chain_id, vset, commit, vset.total_voting_power() * 2 // 3)
    v = pl.AsyncBatchVerifier(depth=2, mesh_lanes=2)
    try:
        futs = [v.submit(b) for b in blocks]
        res = [np.asarray(f.result(timeout=600)) for f in futs]
        drain_pool(v._pool)
        pool = v._pool.stats()
    finally:
        v.close()
    pipe_ok = all(
        np.array_equal(r, np.asarray(backend.verify_batch(b)))
        for b, r in zip(blocks, res)
    )
    print(f"  pipeline mesh worker parity     : "
          f"{'OK' if pipe_ok else 'MISMATCH'}")
    print(f"  pool                            : {pool}")
    if not pipe_ok:
        rc = 1
    if pool["in_flight"] != 0:
        print(f"  FAIL: {pool['in_flight']} pool slots leaked",
              file=sys.stderr)
        rc = 1

    # -- secp lane: device row == host per-signature loop ---------------
    n_lane = 16
    lane = []
    for i in range(n_lane):
        sk = _secp.PrivKey((7000 + i).to_bytes(32, "big"))
        m = b"lane-%d" % i
        lane.append((sk.pub_key(), m, sk.sign(m)))
    # one tampered, one non-lower-S (upper-S re-encoding of a valid sig)
    pk3, m3, s3 = lane[3]
    lane[3] = (pk3, m3, s3[:32] + s3[32:][::-1])
    pk5, m5, s5 = lane[5]
    s_hi = int.from_bytes(s5[32:], "big")
    n_order = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
    lane[5] = (pk5, m5, s5[:32] + (n_order - s_hi).to_bytes(32, "big"))
    host = np.asarray([pk.verify_signature(m, s) for pk, m, s in lane])
    dev = np.asarray(backend.verify_batch_secp(
        [(pk.bytes(), m, s) for pk, m, s in lane]))
    lane_ok = (np.array_equal(host, dev) and not dev[3] and not dev[5]
               and dev.sum() == n_lane - 2)
    print(f"  secp device vs host lane        : "
          f"{'OK' if lane_ok else 'MISMATCH'} "
          f"(rejected {n_lane - int(dev.sum())}/{n_lane}: tampered + "
          "non-lower-S)")
    if not lane_ok:
        rc = 1
    return rc


def run_bls(args) -> int:
    """--bls: the ISSUE 20 aggregation-lane gate. K aggregated commits
    (ONE BLS signature + a signer bitmap each) must verify in a single
    fused multi-pairing launch with verdicts AND blame byte-identical
    to the pure-Python reference walk (crypto/bls12381.py). Every
    kernel runs REAL — correctness is the gate; throughput is
    `bench.py bls` (AGG_r*.json). Asserts:

      wire     AggregatedCommit proto roundtrip, and the commit ships
               96 sig bytes + ceil(V/8) bitmap bytes instead of V
               per-signature rows
      codes    the fused K=4 launch (good / forged / non-subgroup sig /
               non-subgroup pubkey) returns exactly the verdict codes
               the host prep + kernel contract pins — including a
               CRAFTED on-curve-but-out-of-subgroup G2 signature and
               G1 pubkey (rejecting those is what makes apk
               aggregation sound)
      blame    conclude() raises the EXACT string of the sequential
               verify_aggregated_commit walk for every row — pairing
               failure, subgroup sig, subgroup pubkey (validator #i),
               and the pre-crypto wrong-bitmap-size reject
      lanes    an ed25519 + secp256k1 + bls12381 three-lane superbatch
               builds ONE SchemeSuperBlock (BLS segment at its
               quantized width 4, NOT the per-sig lane bucket) and a
               single launch fn call verifies all three lanes; the
               async pipeline fuses the same three submissions into
               ONE dispatch (launch count from the tracer)
      no leak  zero buffer-pool slots in flight once drained
    """
    import jax

    from tendermint_tpu.libs import jaxcache

    jaxcache.enable(jax, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    os.environ["TM_TPU_MESH_LANE_BUCKET"] = "16"

    from tendermint_tpu.crypto import bls12381 as bls
    from tendermint_tpu.crypto import ed25519 as _ed
    from tendermint_tpu.crypto import secp256k1 as _secp
    from tendermint_tpu.libs.bits import BitArray
    from tendermint_tpu.observability import trace as tr
    from tendermint_tpu.ops import backend, device_pool as dp, mesh as ms
    from tendermint_tpu.ops import epoch_cache as _epoch
    from tendermint_tpu.ops import pipeline as pl
    from tendermint_tpu.ops._testing import drain_pool
    from tendermint_tpu.ops.entry_block import EntryBlock
    from tendermint_tpu.types import BlockID, PartSetHeader, Validator, ValidatorSet
    from tendermint_tpu.types import validation as V
    from tendermint_tpu.types.block import AggregatedCommit
    from tendermint_tpu.types.validation import ErrInvalidCommitSignatures

    chain_id = "bls-gate"
    rc = 0

    # -- craft on-curve, out-of-subgroup points (the subgroup rows) -----
    def bad_g1():
        x = 1
        while True:
            y2 = (x * x * x + bls.B) % bls.P
            y = bls.fp_sqrt(y2)
            if y is not None and not bls.g1_in_subgroup((x, y)):
                return bls.g1_compress((x, y))
            x += 1

    def bad_g2():
        c = 1
        while True:
            x = (c, 0)
            y2 = bls.f2_add(bls.f2_mul(x, bls.f2_sqr(x)),
                            bls.f2_scalar(bls.XI, bls.B))
            y = bls.f2_sqrt(y2)
            if y is not None and not bls.g2_in_subgroup((x, y)):
                return bls.g2_compress((x, y))
            c += 1

    rogue_pub, rogue_sig = bad_g1(), bad_g2()
    st_pub = bls.pubkey_status(rogue_pub)[1]
    st_sig = bls.signature_status(rogue_sig)[1]
    print(f"prep_bench --bls: crafted subgroup violations "
          f"(pub={st_pub}, sig={st_sig}) lane_bucket=16")
    if st_pub != "subgroup" or st_sig != "subgroup":
        print("  FAIL: crafted points must decompress on-curve but fail "
              "the subgroup check", file=sys.stderr)
        return 1

    # -- committee: 7 real signers + 1 rogue (non-subgroup) pubkey ------
    n_vals = 8
    sks = [bls.PrivKey((i + 1).to_bytes(32, "big")) for i in range(7)]
    vals = [Validator.new(sk.pub_key(), 100) for sk in sks]
    vals.append(Validator.new(bls.PubKey(rogue_pub), 100))
    vset = ValidatorSet.new(vals)
    by_addr = {sk.pub_key().address(): sk for sk in sks}
    order = [by_addr.get(v.address) for v in vset.validators]
    rogue_idx = order.index(None)
    real = [i for i in range(n_vals) if i != rogue_idx]
    bid = BlockID(hash=b"\x14" * 32,
                  part_set_header=PartSetHeader(total=1, hash=b"\x15" * 32))

    def make_agg(signers, forge=False, sig=None):
        ba = BitArray(n_vals)
        for i in signers:
            ba.set_index(i, True)
        agg = AggregatedCommit(height=9, round=0, block_id=bid, signers=ba)
        if sig is not None:
            agg.signature = sig
            return agg
        msg = agg.sign_bytes(chain_id)
        parts = [order[i].sign(msg) for i in signers if order[i] is not None]
        if forge:
            parts[-1] = order[signers[-1]].sign(b"not-the-vote")
        agg.signature = bls.aggregate(parts)
        return agg

    def seq_error(agg):
        try:
            V.verify_aggregated_commit(chain_id, vset, bid, 9, agg)
            return None
        except ValueError as e:
            return str(e)

    # -- wire: proto roundtrip + aggregated footprint -------------------
    good = make_agg(real[:6])
    dec = AggregatedCommit.decode(good.encode())
    wire = len(good.encode())
    print(f"  wire : roundtrip={'OK' if dec == good else 'FAIL'} "
          f"bytes={wire} (96B sig + {(n_vals + 7) // 8}B bitmap, "
          f"not {n_vals} per-sig rows)")
    if dec != good:
        rc = 1

    # -- fused K=4 launch: codes + blame parity -------------------------
    _epoch.reset(8)
    _epoch.note_valset(vset)
    _epoch.note_valset(vset)
    aggs = [
        good,                                       # valid
        make_agg(real[:6], forge=True),             # pairing failure
        make_agg(real[:6], sig=rogue_sig),          # non-subgroup sig
        make_agg(real[:5] + [rogue_idx]),           # non-subgroup pubkey
    ]
    want_errs = [seq_error(a) for a in aggs]
    pairs = [V.prepare_aggregated_commit(chain_id, vset, bid, 9, a, k_hint=4)
             for a in aggs]
    fused = ms.block_concat([blk for blk, _ in pairs])
    t0 = time.perf_counter()
    codes = np.asarray(backend.verify_batch_bls_codes(fused))
    dt = time.perf_counter() - t0
    from tendermint_tpu.ops import bls_verify as bv
    want_codes = [bv.CODE_VALID, bv.CODE_PAIRING, bv.CODE_SIG["subgroup"],
                  bv.CODE_PUB_BASE + rogue_idx]
    print(f"  codes: {codes.tolist()} want={want_codes} "
          f"(K=4 fused, {dt:.1f}s)")
    if codes.tolist() != want_codes:
        print("  FAIL: fused launch verdict codes diverge from the "
              "host-prep/kernel contract", file=sys.stderr)
        rc = 1
    mism = []
    for j, ((_, conc), want) in enumerate(zip(pairs, want_errs)):
        try:
            conc(codes[j:j + 1])
            got = None
        except ValueError as e:
            got = str(e)
        if got != want:
            mism.append((j, want, got))
    print(f"  blame: {'OK (4/4 byte-identical)' if not mism else 'MISMATCH'}")
    for j, want, got in mism:
        print(f"  FAIL row {j}: sequential={want!r} batched={got!r}",
              file=sys.stderr)
        rc = 1

    # -- wrong bitmap size: pre-crypto reject, parity, zero launches ----
    short = make_agg(real[:6])
    short.signers = BitArray(n_vals + 3)
    for i in real[:6]:
        short.signers.set_index(i, True)
    errs = []
    for fn in (lambda: V.verify_aggregated_commit(chain_id, vset, bid, 9, short),
               lambda: V.prepare_aggregated_commit(chain_id, vset, bid, 9,
                                                   short, k_hint=4)):
        try:
            fn()
            errs.append(None)
        except ErrInvalidCommitSignatures as e:
            errs.append(str(e))
    bitmap_ok = errs[0] is not None and errs[0] == errs[1]
    print(f"  bitmap: {'OK' if bitmap_ok else 'FAIL'} "
          f"(both paths: {errs[0]!r})")
    if not bitmap_ok:
        rc = 1

    # -- three-lane superbatch: one plan, one launch fn call ------------
    def ed_block(n, bad=()):
        rows = []
        for i in range(n):
            sk = _ed.gen_priv_key((5000 + i).to_bytes(32, "little"))
            m = b"agg-ed-%d" % i
            rows.append((sk.pub_key().bytes(), m,
                         sk.sign(m) if i not in bad else b"\x07" * 64))
        return EntryBlock.from_entries(rows)

    def secp_block(n, bad=()):
        rows = []
        for i in range(n):
            sk = _secp.PrivKey((6000 + i).to_bytes(32, "big"))
            m = b"agg-secp-%d" % i
            rows.append((sk.pub_key().bytes(), m,
                         sk.sign(m) if i not in bad else b"\x07" * 64))
        return EntryBlock.from_entries(rows, scheme="secp256k1")

    class _J:
        def __init__(self, blk):
            self.entries = blk

    blocks = [ed_block(10, bad=(4,)), secp_block(7, bad=(2,)), fused]
    jobs = [_J(b) for b in blocks]
    plan, held = ms.pack_jobs(jobs, 4)
    sblock, spans = ms.build_superblock(plan)
    is_super = isinstance(sblock, ms.SchemeSuperBlock)
    parts = [(s, len(b)) for s, b, _ in sblock.parts] if is_super else []
    bls_w = dict((s, n) for s, n in parts).get("bls12381")
    print(f"  lanes: schemes={plan.schemes()} parts={parts} "
          f"rows={plan.bucket}")
    if held or not is_super or bls_w != 4:
        print("  FAIL: three-lane plan must build one SchemeSuperBlock "
              "with the BLS segment at quantized width 4", file=sys.stderr)
        rc = 1
    res = ms.prepare_superbatch(sblock, plan)
    f, fargs = res[0], res[1]
    shardings = res[4] if len(res) > 4 else None
    arr = np.asarray(f(*dp.transfer(fargs, shardings=shardings)))
    by_job = {id(j): (off, n) for j, off, n in spans}
    lane_ok = True
    for j, want in ((jobs[0], np.asarray(backend.verify_batch(blocks[0]))),
                    (jobs[1], np.asarray(backend.verify_batch(blocks[1])))):
        off, n = by_job[id(j)]
        lane_ok &= np.array_equal(arr[off:off + n].astype(bool), want)
    off, n = by_job[id(jobs[2])]
    lane_ok &= arr[off:off + n].tolist() == want_codes
    print(f"  launch: 1 fn call, demux parity "
          f"{'OK' if lane_ok else 'MISMATCH'} "
          f"(ed25519 + secp256k1 bool rows, bls12381 code row)")
    if not lane_ok:
        rc = 1

    # -- pipeline: three submissions fuse into ONE dispatch, no leak ----
    tr.TRACER.clear()
    tr.configure(enabled=True)
    v = pl.AsyncBatchVerifier(depth=2, mesh_lanes=4)
    try:
        futs = [v.submit(b) for b in blocks]
        rows = [np.asarray(fu.result(timeout=600)) for fu in futs]
        drain_pool(v._pool)
        pool = v._pool.stats()
    finally:
        tr.configure(enabled=False)
        v.close()
    launches = sum(1 for name, *_ in tr.TRACER.events()
                   if name == "pipeline.dispatch")
    pipe_ok = (np.array_equal(rows[0].astype(bool),
                              np.asarray(backend.verify_batch(blocks[0])))
               and np.array_equal(rows[1].astype(bool),
                                  np.asarray(backend.verify_batch(blocks[1])))
               and rows[2].tolist() == want_codes)
    print(f"  pipeline: launches={launches} parity="
          f"{'OK' if pipe_ok else 'MISMATCH'} pool={pool}")
    if launches != 1:
        print(f"  FAIL: three same-window submissions must fuse into one "
              f"dispatch, saw {launches}", file=sys.stderr)
        rc = 1
    if not pipe_ok:
        rc = 1
    if pool["in_flight"] != 0:
        print(f"  FAIL: {pool['in_flight']} pool slots leaked",
              file=sys.stderr)
        rc = 1
    return rc


def run_light(args) -> int:
    """--light: the round-11 light-service gate on a mocked relay (slow
    readback over REAL kernels — verdicts are live). Asserts the three
    properties the batched service must hold:

      coalesce  cross-request SAME-EPOCH coalescing proven by launch
                count: R warm requests emit 2R-1 stage blocks but the
                shared pipeline fuses them into far fewer device
                launches (each undersized per-request dispatch would
                otherwise pay a full relay RTT — the ~1.2k headers/s
                sequential ceiling)
      parity    verdicts AND blame byte-identical to the sequential
                light/verifier.py path — ok requests, a forged-commit
                request (tampered signature) and an expired-trusted-
                header request all match (type name + error string)
      no leak   zero buffer-pool slots in flight once drained, and a
                memoized resubmission adds ZERO launches
    """
    import jax

    from tendermint_tpu.libs import jaxcache

    jaxcache.enable(jax, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from dataclasses import replace as dc_replace

    import bench as _bench

    from tendermint_tpu.light import verifier as lv
    from tendermint_tpu.light.batch import HeaderRequest, fingerprint
    from tendermint_tpu.light.service import LightVerifyService
    from tendermint_tpu.observability import trace as tr
    from tendermint_tpu.ops import epoch_cache as _epoch
    from tendermint_tpu.ops import pipeline as pl
    from tendermint_tpu.ops._testing import drain_pool, slow_prepare
    from tendermint_tpu.types.block import Commit
    from tendermint_tpu.wire.canonical import Timestamp

    n_vals, n_headers = 8, 6
    resolve_delay = 0.15
    chain_id = "light-gate"
    print(f"prep_bench --light: vals={n_vals} headers={n_headers} "
          f"resolve_delay={resolve_delay}s")
    rc = 0
    shs = _bench._build_header_chain(chain_id, n_headers, n_vals)
    trusted, vset = shs[0]
    now = Timestamp(seconds=1_600_000_000 + n_headers + 60)
    period = 1e9

    def mkreq(k, untrusted=None, p=period):
        return HeaderRequest(
            trusted_header=trusted, trusted_vals=vset,
            untrusted_header=untrusted or shs[k][0],
            untrusted_vals=vset, trusting_period=p,
        )

    def seq_verdict(req):
        try:
            lv.verify(req.trusted_header, req.trusted_vals,
                      req.untrusted_header, req.untrusted_vals,
                      req.trusting_period, now, req.max_clock_drift,
                      req.trust_level)
            return None
        except Exception as e:  # noqa: BLE001 — the verdict IS the error
            return (type(e).__name__, str(e))

    # warm epoch: one valset across every request, device tables resident
    _epoch.reset(4)
    # adversarial inputs: a forged commit (tampered signature) and an
    # expired trusted header, alongside the clean warm requests
    fcommit = Commit.decode(shs[3][0].commit.encode())
    fcommit.signatures[4] = dc_replace(
        fcommit.signatures[4], signature=b"\x07" * 64
    )
    from tendermint_tpu.types import SignedHeader

    forged = SignedHeader(header=shs[3][0].header, commit=fcommit)
    reqs = [mkreq(k) for k in range(1, n_headers + 1)]
    reqs.append(mkreq(3, untrusted=forged))
    reqs.append(mkreq(5, p=1.0))  # trusted header long expired
    n_stage_blocks = 1 + (n_headers - 1) * 2 + 2 + 0  # adjacent:1, non-adj:2 each, forged:2, expired:0
    assert len({fingerprint(r, now) for r in reqs}) == len(reqs)

    real_prepare = pl.AsyncBatchVerifier._prepare
    pl.AsyncBatchVerifier._prepare = staticmethod(
        slow_prepare(real_prepare, resolve_delay)
    )
    tr.TRACER.clear()
    tr.configure(enabled=True)
    v = pl.AsyncBatchVerifier(depth=1, pool_depth=OVERLAP_POOL_DEPTH)
    svc = LightVerifyService(verifier=v)
    try:
        res = svc.submit_many(reqs, now=now).results(timeout=900)
        launches1 = sum(
            1 for name, *_ in tr.TRACER.events() if name == "pipeline.dispatch"
        )
        # memoized resubmission: byte-identical requests resolve from the
        # verdict memo with ZERO additional device work
        res2 = svc.submit_many(reqs, now=now).results(timeout=120)
        launches2 = sum(
            1 for name, *_ in tr.TRACER.events() if name == "pipeline.dispatch"
        )
        drain_pool(v._pool)
        pool = v._pool.stats()
        stats = svc.stats()
    finally:
        tr.configure(enabled=False)
        svc.close()
        v.close()
        pl.AsyncBatchVerifier._prepare = real_prepare

    # -- parity vs the sequential verifier ------------------------------
    mism = []
    for i, (req, r) in enumerate(zip(reqs, res)):
        want = seq_verdict(req)
        got = None if r["ok"] else (r["error_type"], r["error"])
        if want != got:
            mism.append((i, want, got))
    ok_count = sum(1 for r in res if r["ok"])
    print(f"  requests={len(reqs)} ok={ok_count} "
          f"rejected={len(reqs) - ok_count}")
    print(f"  verdict/blame parity vs sequential : "
          f"{'OK' if not mism else f'MISMATCH {mism[:2]}'}")
    if mism:
        rc = 1
    if [r["ok"] for r in res2] != [r["ok"] for r in res]:
        print("  FAIL: memoized verdicts differ from first pass",
              file=sys.stderr)
        rc = 1

    # -- cross-request coalescing by launch count ------------------------
    print(f"  stage blocks submitted             : {n_stage_blocks}")
    print(f"  device launches (first pass)       : {launches1}")
    print(f"  device launches (memo resubmission): {launches2 - launches1}")
    if launches1 >= n_stage_blocks:
        print(f"  FAIL: {launches1} launches for {n_stage_blocks} stage "
              "blocks — no cross-request coalescing", file=sys.stderr)
        rc = 1
    if launches2 != launches1:
        print("  FAIL: memoized resubmission launched device work",
              file=sys.stderr)
        rc = 1
    if stats["memo_hits"] != len(reqs):
        print(f"  FAIL: expected {len(reqs)} memo hits, got "
              f"{stats['memo_hits']}", file=sys.stderr)
        rc = 1

    # -- epoch grouping + pool hygiene -----------------------------------
    est = _epoch.stats()
    print(f"  epoch cache                        : entries={est['entries']} "
          f"hits={est['hits']} misses={est['misses']}")
    print(f"  pool                               : {pool}")
    if pool["in_flight"] != 0:
        print(f"  FAIL: {pool['in_flight']} pool slots leaked",
              file=sys.stderr)
        rc = 1
    if est["hits"] <= 0:
        print("  FAIL: warm-epoch requests never hit the epoch cache",
              file=sys.stderr)
        rc = 1
    return rc


def run_ingress(args) -> int:
    """--ingress: the round-13 mempool-ingress gate on a mocked relay
    (slow readback over REAL kernels — verdicts are live). Asserts the
    three properties device-batched CheckTx must hold:

      fuse       N flooded txs reach the device in <= K launches (the
                 accumulator windows them, the coalescer fuses windows) —
                 each per-tx dispatch would otherwise pay a full relay
                 RTT, the ~25 tx/s sequential ceiling bench.py measures
      QoS        a consensus-priority batch submitted mid-flood overtakes
                 queued ingress work: preempted_total advances and the
                 commit's verdict lands while ingress futures are still
                 outstanding
      no leak    every tx future resolves (a forged signature resolves
                 FALSE, never silently dropped), and zero buffer-pool
                 slots remain in flight once drained
    """
    import jax

    from tendermint_tpu.libs import jaxcache

    jaxcache.enable(jax, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from tendermint_tpu.crypto import ed25519 as ed
    from tendermint_tpu.mempool import ingress as ing
    from tendermint_tpu.observability import trace as tr
    from tendermint_tpu.ops import epoch_cache as _epoch
    from tendermint_tpu.ops import pipeline as pl
    from tendermint_tpu.ops._testing import drain_pool, slow_prepare
    from tendermint_tpu.ops.entry_block import EntryBlock

    n_txs, n_senders, max_batch = 256, 8, 64
    resolve_delay = 0.15
    print(f"prep_bench --ingress: txs={n_txs} senders={n_senders} "
          f"batch={max_batch} resolve_delay={resolve_delay}s")
    rc = 0
    import hashlib

    privs = [ed.gen_priv_key(seed=hashlib.sha256(b"ingress-gate-%d" % s)
                             .digest()) for s in range(n_senders)]
    stxs = []
    for i in range(n_txs):
        raw = ing.make_signed_tx(privs[i % n_senders],
                                 b"gate_k%d=v%d" % (i, i),
                                 nonce=i // n_senders + 1)
        stxs.append(ing.parse_signed_tx(raw))
    # one forged signature mid-flood: its future must resolve FALSE
    forged_i = n_txs // 2
    f = stxs[forged_i]
    bad = bytearray(f.sig)
    bad[0] ^= 0x5A
    stxs[forged_i] = ing.SignedTx(f.scheme, f.pub, f.nonce, bytes(bad),
                                  f.payload, f.raw)
    commit_block = EntryBlock.from_entries(
        [(s.pub, s.signed_bytes(), s.sig) for s in stxs[:32]
         if ing.host_verify(s)]
    )

    _epoch.reset(4)
    real_prepare = pl.AsyncBatchVerifier._prepare
    pl.AsyncBatchVerifier._prepare = staticmethod(
        slow_prepare(real_prepare, resolve_delay)
    )
    tr.TRACER.clear()
    tr.configure(enabled=True)
    os.environ["TM_TPU_FORCE_DEVICE"] = "1"
    v = pl.AsyncBatchVerifier(depth=1, pool_depth=OVERLAP_POOL_DEPTH)
    acc = ing.IngressAccumulator(verifier=v, max_batch=max_batch,
                                 window_ms=8.0)
    try:
        # two waves: wave 1 launches and holds the single depth slot for
        # resolve_delay; wave 2 transfers and parks on the semaphore.
        # The commit then arrives against a genuinely occupied pipeline —
        # the shape the preemption machinery exists for.
        futs = [acc.submit(s) for s in stxs[:max_batch]]
        acc.flush_now()
        time.sleep(0.05)  # wave 1 is in flight on the device
        futs += [acc.submit(s) for s in stxs[max_batch:]]
        acc.flush_now()
        time.sleep(0.02)  # wave 2 transferred, parked on the depth sem
        cfut = v.submit(commit_block, priority=pl.PRIORITY_CONSENSUS)
        commit_ok = bool(all(cfut.result(timeout=300)))
        pending_at_commit = sum(1 for x in futs if not x.done())
        verdicts = [x.result(timeout=300) for x in futs]
        launches = sum(
            1 for name, *_ in tr.TRACER.events()
            if name == "pipeline.dispatch"
        )
        drain_pool(v._pool)
        pool = v._pool.stats()
        preempts = v.preempted_total
    finally:
        tr.configure(enabled=False)
        acc.close()
        v.close()
        os.environ.pop("TM_TPU_FORCE_DEVICE", None)
        pl.AsyncBatchVerifier._prepare = real_prepare

    # -- fuse: N txs in <= K launches ------------------------------------
    k_max = n_txs // max_batch + 2  # windows + the commit + slack
    print(f"  txs flooded                : {n_txs}")
    print(f"  device launches            : {launches} (gate: <= {k_max})")
    if launches > k_max:
        print(f"  FAIL: {launches} launches for {n_txs} txs — "
              "ingress windows are not fusing", file=sys.stderr)
        rc = 1

    # -- QoS: the commit overtook queued ingress work --------------------
    print(f"  commit verdict             : "
          f"{'all-valid' if commit_ok else 'INVALID'}")
    print(f"  ingress futures pending when commit landed: "
          f"{pending_at_commit}")
    print(f"  preempted_total            : {preempts}")
    if not commit_ok:
        print("  FAIL: consensus batch verdict wrong", file=sys.stderr)
        rc = 1
    if preempts <= 0:
        print("  FAIL: consensus batch never preempted queued ingress "
              "work", file=sys.stderr)
        rc = 1
    if pending_at_commit <= 0:
        print("  FAIL: commit landed after the whole flood — no QoS "
              "evidence", file=sys.stderr)
        rc = 1

    # -- verdict integrity + pool hygiene --------------------------------
    bad_verdicts = [i for i, ok in enumerate(verdicts)
                    if ok != (i != forged_i)]
    print(f"  verdicts                   : {sum(verdicts)} valid / "
          f"{len(verdicts) - sum(verdicts)} rejected "
          f"(forged tx at {forged_i})")
    print(f"  pool                       : {pool}")
    if bad_verdicts:
        print(f"  FAIL: wrong verdicts at {bad_verdicts[:4]} — the "
              "forged tx must be the ONLY rejection", file=sys.stderr)
        rc = 1
    if pool["in_flight"] != 0:
        print(f"  FAIL: {pool['in_flight']} pool slots leaked",
              file=sys.stderr)
        rc = 1
    return rc


def run_votes(args) -> int:
    """--votes: the round-15 live-vote-ingress gate on a mocked relay
    (slow readback over REAL kernels — verdicts are live). Asserts the
    three properties device-batched AddVote must hold:

      fuse       N gossiped votes reach the device in <= K launches (the
                 accumulator windows them by (height, valset epoch), the
                 coalescer fuses windows) — per-vote dispatch would pay a
                 full relay RTT each
      parity     a forged signature mid-flood resolves FALSE and is the
                 ONLY rejection — blame lands on exactly the forged vote
      no leak    every vote's verdict arrives (none silently dropped)
                 and zero buffer-pool slots remain in flight once drained
    """
    import threading

    import jax

    from tendermint_tpu.libs import jaxcache

    jaxcache.enable(jax, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from tendermint_tpu.consensus import vote_ingress as vi
    from tendermint_tpu.crypto import ed25519 as ed
    from tendermint_tpu.observability import trace as tr
    from tendermint_tpu.ops import epoch_cache as _epoch
    from tendermint_tpu.ops import pipeline as pl
    from tendermint_tpu.ops._testing import drain_pool, slow_prepare
    from tendermint_tpu.types.block import BlockID, PartSetHeader
    from tendermint_tpu.types.validator_set import Validator, ValidatorSet
    from tendermint_tpu.types.vote import PREVOTE_TYPE, Vote
    from tendermint_tpu.wire.canonical import Timestamp

    chain_id = "votes-gate"
    n_vals, n_rounds, max_batch = 32, 8, 64
    n_votes = n_vals * n_rounds
    resolve_delay = 0.15
    print(f"prep_bench --votes: votes={n_votes} vals={n_vals} "
          f"rounds={n_rounds} batch={max_batch} "
          f"resolve_delay={resolve_delay}s")
    rc = 0

    pairs = []
    for i in range(n_vals):
        sk = ed.gen_priv_key(bytes([i + 1]) * 32)
        pairs.append((sk, Validator.new(sk.pub_key(), 100)))
    vset = ValidatorSet.new([v for _, v in pairs])
    by_addr = {v.address: sk for sk, v in pairs}
    sks = [by_addr[v.address] for v in vset.validators]
    bid = BlockID(hash=b"\x07" * 32,
                  part_set_header=PartSetHeader(total=1, hash=b"\x07" * 32))
    height = 10

    pends = []
    for r in range(n_rounds):
        for i, sk in enumerate(sks):
            vote = Vote(
                type=PREVOTE_TYPE, height=height, round=r, block_id=bid,
                timestamp=Timestamp(seconds=1_600_000_000, nanos=0),
                validator_address=vset.validators[i].address,
                validator_index=i,
            )
            msg = vote.sign_bytes(chain_id)
            vote = Vote(**{**vote.__dict__, "signature": sk.sign(msg)})
            pends.append(vi.PendingVote(
                vote, "gate-peer", sk.pub_key().bytes(), msg,
                t_enq=time.perf_counter(),
            ))
    # one forged signature mid-flood: its verdict must be the ONLY False
    forged_i = n_votes // 2
    f = pends[forged_i]
    bad = bytearray(f.vote.signature)
    bad[0] ^= 0x5A
    fv = Vote(**{**f.vote.__dict__, "signature": bytes(bad)})
    pends[forged_i] = vi.PendingVote(fv, f.peer_id, f.pub, f.msg,
                                     t_enq=f.t_enq)

    _epoch.reset(4)
    _epoch.note_valset(vset)  # register
    _epoch.note_valset(vset)  # warm: windows attach val_idx + epoch_key
    verdicts: dict = {}
    done = threading.Event()

    def collect(batch, vds, err):
        for i, p in enumerate(batch):
            key = (p.vote.round, p.vote.validator_index)
            verdicts[key] = None if err is not None else bool(vds[i])
        if len(verdicts) >= n_votes:
            done.set()

    real_prepare = pl.AsyncBatchVerifier._prepare
    pl.AsyncBatchVerifier._prepare = staticmethod(
        slow_prepare(real_prepare, resolve_delay)
    )
    tr.TRACER.clear()
    tr.configure(enabled=True)
    os.environ["TM_TPU_FORCE_DEVICE"] = "1"
    v = pl.AsyncBatchVerifier(depth=2, pool_depth=OVERLAP_POOL_DEPTH)
    acc = vi.VoteIngress(collect, verifier=v, max_batch=max_batch,
                         window_ms=8.0)
    try:
        for p in pends:
            acc.submit(p, vset)
        acc.flush_now()
        if not done.wait(timeout=300):
            print(f"  FAIL: only {len(verdicts)}/{n_votes} verdicts "
                  "arrived", file=sys.stderr)
            rc = 1
        launches = sum(1 for name, *_ in tr.TRACER.events()
                       if name == "pipeline.dispatch")
        drain_pool(v._pool)
        pool = v._pool.stats()
        stats = acc.stats()
    finally:
        tr.configure(enabled=False)
        acc.close()
        v.close()
        os.environ.pop("TM_TPU_FORCE_DEVICE", None)
        pl.AsyncBatchVerifier._prepare = real_prepare

    # -- fuse: N votes in <= K launches ----------------------------------
    k_max = n_votes // max_batch + 2  # windows + slack
    print(f"  votes flooded              : {n_votes}")
    print(f"  device launches            : {launches} (gate: <= {k_max}, "
          f"per-vote would be {n_votes})")
    print(f"  ingress stats              : batches={stats['batches']} "
          f"sigs={stats['sigs']} sync_fallbacks={stats['sync_fallbacks']}")
    if launches > k_max:
        print(f"  FAIL: {launches} launches for {n_votes} votes — vote "
              "windows are not fusing", file=sys.stderr)
        rc = 1

    # -- parity: exactly the forged vote rejected ------------------------
    bad_keys = [k for k, ok in verdicts.items()
                if ok != ((k[0], k[1]) != (forged_i // n_vals,
                                           forged_i % n_vals))]
    n_ok = sum(1 for x in verdicts.values() if x)
    print(f"  verdicts                   : {n_ok} valid / "
          f"{len(verdicts) - n_ok} rejected (forged vote at round "
          f"{forged_i // n_vals} idx {forged_i % n_vals})")
    if bad_keys:
        print(f"  FAIL: wrong verdicts at {bad_keys[:4]} — the forged "
              "vote must be the ONLY rejection", file=sys.stderr)
        rc = 1

    # -- pool hygiene ----------------------------------------------------
    print(f"  pool                       : {pool}")
    if pool["in_flight"] != 0:
        print(f"  FAIL: {pool['in_flight']} pool slots leaked",
              file=sys.stderr)
        rc = 1
    return rc


def _build_replay_chain(n_blocks: int, n_vals: int, chain_id: str,
                        rotate_at=()):
    """Fully-linked signed chain for the replay gate: block h+1's
    last_commit signs block h's BlockID (hash + part-set header of the
    encoded block), real keys, optional valset rotation."""
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.types.block import (
        Block,
        BlockID,
        Data,
        Header,
        Version,
    )
    from tendermint_tpu.types.part_set import BLOCK_PART_SIZE_BYTES, PartSet
    from tendermint_tpu.types.validator_set import Validator, ValidatorSet
    from tendermint_tpu.types.vote import PRECOMMIT_TYPE, Vote
    from tendermint_tpu.types.vote_set import VoteSet
    from tendermint_tpu.wire.canonical import Timestamp

    def mk_vals(seed):
        pairs = []
        for i in range(n_vals):
            sk = ed25519.gen_priv_key(bytes([seed + i]) * 32)
            pairs.append((sk, Validator.new(sk.pub_key(), 100)))
        vset = ValidatorSet.new([v for _, v in pairs])
        by_addr = {v.address: sk for sk, v in pairs}
        return [by_addr[v.address] for v in vset.validators], vset

    rotate_at = sorted(rotate_at)
    vals_at, keys_at = {}, {}
    seed, cur = 1, mk_vals(1)
    for h in range(1, n_blocks + 2):
        if h in rotate_at:
            seed += n_vals
            cur = mk_vals(seed)
        keys_at[h], vals_at[h] = cur
    blocks, last_commit, prev_bid = [], None, BlockID()
    for h in range(1, n_blocks + 1):
        hdr = Header(
            version=Version(block=11, app=0), chain_id=chain_id, height=h,
            time=Timestamp(seconds=1_600_000_000 + h), last_block_id=prev_bid,
            validators_hash=vals_at[h].hash(),
            next_validators_hash=vals_at[h + 1].hash(),
            consensus_hash=b"\x01" * 32, app_hash=b"",
            proposer_address=vals_at[h].validators[0].address,
        )
        block = Block(header=hdr, data=Data(), last_commit=last_commit)
        block.fill_header()
        parts = PartSet.from_data(block.encode(), BLOCK_PART_SIZE_BYTES)
        bid = BlockID(hash=block.hash(), part_set_header=parts.header())
        vs = VoteSet(chain_id, h, 0, PRECOMMIT_TYPE, vals_at[h])
        for sk in keys_at[h]:
            addr = sk.pub_key().address()
            idx, _ = vals_at[h].get_by_address(addr)
            vote = Vote(
                type=PRECOMMIT_TYPE, height=h, round=0, block_id=bid,
                timestamp=Timestamp(seconds=1_600_000_000, nanos=0),
                validator_address=addr, validator_index=idx,
            )
            sig = sk.sign(vote.sign_bytes(chain_id))
            vs.add_vote(Vote(**{**vote.__dict__, "signature": sig}))
        last_commit = vs.make_commit()
        prev_bid = bid
        blocks.append(block)
    return blocks, vals_at


def run_replay(args) -> int:
    """--replay: the round-14 chain-replay gate on a mocked relay (slow
    readback over REAL kernels — verdicts are live). Asserts the three
    properties range-batched blocksync must hold:

      pack       a window of W same-epoch heights reaches the device in
                 ceil(W*sigs/bucket) launches, NOT W — the whole point
                 of range batching vs the verify-one-ahead path
      parity     a forged commit mid-range falls back to per-height
                 sequential verification whose rejection error is
                 byte-identical to verify_commit_light's, and every
                 height before the forgery still applies
      no leak    zero buffer-pool slots remain in flight once drained
    """
    import jax

    from tendermint_tpu.libs import jaxcache

    jaxcache.enable(jax, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from tendermint_tpu.blocksync.replay import ReplayEngine
    from tendermint_tpu.observability import trace as tr
    from tendermint_tpu.ops import backend
    from tendermint_tpu.ops import pipeline as pl
    from tendermint_tpu.ops._testing import drain_pool, slow_prepare
    from tendermint_tpu.types.block import BlockID
    from tendermint_tpu.types.part_set import BLOCK_PART_SIZE_BYTES, PartSet
    from tendermint_tpu.types.validation import verify_commit_light

    chain_id = "replay-gate"
    n_blocks, n_vals = 13, 8  # 12 verifiable heights x ~6 light-path sigs
    resolve_delay = 0.05
    print(f"prep_bench --replay: blocks={n_blocks} vals={n_vals} "
          f"resolve_delay={resolve_delay}s")
    rc = 0
    blocks, vals_at = _build_replay_chain(n_blocks, n_vals, chain_id)

    class _St:
        def __init__(self):
            self.chain_id = chain_id
            self.validators = vals_at[1]
            self.last_block_height = 0

    def mk_cbs(st):
        saves = []

        def save(block, parts, seen_commit):
            saves.append(block.header.height)

        def apply(bid, block):
            st.last_block_height = block.header.height
            st.validators = vals_at[block.header.height + 1]
            return st

        return saves, save, apply

    real_prepare = pl.AsyncBatchVerifier._prepare
    pl.AsyncBatchVerifier._prepare = staticmethod(
        slow_prepare(real_prepare, resolve_delay)
    )
    tr.TRACER.clear()
    tr.configure(enabled=True)
    os.environ["TM_TPU_FORCE_DEVICE"] = "1"
    v = pl.AsyncBatchVerifier(depth=2, pool_depth=OVERLAP_POOL_DEPTH)
    try:
        # -- pack: W same-epoch heights -> ceil(W*sigs/bucket) launches --
        eng = ReplayEngine(synchronous=True, verifier=v)
        st = _St()
        saves, save, apply = mk_cbs(st)
        st, out = eng.replay_blocks(st, blocks, save, apply)
        launches = sum(1 for name, *_ in tr.TRACER.events()
                       if name == "pipeline.dispatch")
        w = n_blocks - 1
        sigs = eng.sigs_submitted
        bucket = backend.quantized_bucket(max(sigs, 1))
        expect = max(1, -(-sigs // bucket))
        print(f"  heights replayed           : {out.applied} "
              f"(range-verified {out.range_heights})")
        print(f"  sigs submitted             : {sigs} (bucket {bucket})")
        print(f"  device launches            : {launches} "
              f"(gate: <= {expect + 1}, sequential would be {w})")
        if out.applied != w or out.range_heights != w:
            print(f"  FAIL: expected {w} range-verified heights, got "
                  f"{out.range_heights}", file=sys.stderr)
            rc = 1
        if saves != list(range(1, w + 1)):
            print("  FAIL: save order broken", file=sys.stderr)
            rc = 1
        if launches > expect + 1:
            print(f"  FAIL: {launches} launches for {w} heights — range "
                  "packing is not fusing", file=sys.stderr)
            rc = 1

        # -- parity: forged commit mid-range falls back byte-identically -
        blocks2, vals2 = _build_replay_chain(n_blocks, n_vals, chain_id)
        bad_h = 6
        commit = blocks2[bad_h].last_commit  # block 7 vouches for h=6
        s0 = commit.signatures[0]
        commit.signatures[0] = s0.__class__(
            block_id_flag=s0.block_id_flag,
            validator_address=s0.validator_address,
            timestamp=s0.timestamp, signature=bytes(64),
        )
        eng2 = ReplayEngine(synchronous=True, verifier=v)
        st2 = _St()
        saves2, save2, apply2 = mk_cbs(st2)
        st2, out2 = eng2.replay_blocks(st2, blocks2, save2, apply2)
        p = PartSet.from_data(blocks2[bad_h - 1].encode(),
                              BLOCK_PART_SIZE_BYTES)
        bid = BlockID(hash=blocks2[bad_h - 1].hash(),
                      part_set_header=p.header())
        seq_err = None
        try:
            verify_commit_light(chain_id, vals2[bad_h], bid, bad_h,
                                blocks2[bad_h].last_commit)
        except (ValueError, RuntimeError) as e:
            seq_err = str(e)
        print(f"  forged commit at height    : {bad_h}")
        print(f"  applied before rejection   : {out2.applied} "
              f"(gate: {bad_h - 1})")
        print(f"  fallback error             : {out2.error!r}")
        if out2.applied != bad_h - 1 or out2.failed_height != bad_h:
            print(f"  FAIL: fallback applied {out2.applied}, failed at "
                  f"{out2.failed_height}; want {bad_h - 1}/{bad_h}",
                  file=sys.stderr)
            rc = 1
        if seq_err is None or out2.error != seq_err:
            print(f"  FAIL: error mismatch vs sequential path:\n"
                  f"    replay    : {out2.error!r}\n"
                  f"    sequential: {seq_err!r}", file=sys.stderr)
            rc = 1

        drain_pool(v._pool)
        pool = v._pool.stats()
    finally:
        tr.configure(enabled=False)
        v.close()
        os.environ.pop("TM_TPU_FORCE_DEVICE", None)
        pl.AsyncBatchVerifier._prepare = real_prepare

    # -- pool hygiene ----------------------------------------------------
    print(f"  pool                       : {pool}")
    if pool["in_flight"] != 0:
        print(f"  FAIL: {pool['in_flight']} pool slots leaked",
              file=sys.stderr)
        rc = 1
    return rc


def run_fabric(args) -> int:
    """--fabric: the round-17 ingress-fabric gate on a mocked relay
    (slow readback over REAL kernels — verdicts are live). Asserts what
    unifying the four windowed accumulators bought:

      one engine  all four lane patterns (mempool / votes / light /
                  replay) register on ONE engine — exactly one
                  flush-scheduler thread and one completer thread serve
                  all of them, where the per-workload era ran four
      adaptive    the consensus-pattern lane's window moves BOTH ways:
                  it deepens under a flood (grows >= 1) and shrinks back
                  on an idle trickle (shrinks >= 1)
      parity      every signature's verdict arrives and the one forged
                  signature is the ONLY rejection, on the right lane
      no leak     zero buffer-pool slots remain in flight once drained
    """
    import threading

    import jax

    from tendermint_tpu.libs import jaxcache

    jaxcache.enable(jax, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from tendermint_tpu.crypto import ed25519 as ed
    from tendermint_tpu.ops import ingress as fabric
    from tendermint_tpu.ops import pipeline as pl
    from tendermint_tpu.ops._testing import drain_pool, slow_prepare
    from tendermint_tpu.ops.entry_block import EntryBlock

    resolve_delay = 0.05
    n_keys = 8
    keys = [ed.gen_priv_key(bytes([i + 1]) * 32) for i in range(n_keys)]

    def signed(lane: str, i: int):
        sk = keys[i % n_keys]
        msg = f"fabric/{lane}/{i}".encode()
        return (sk.pub_key().bytes(), msg, sk.sign(msg), i)

    rc = 0
    print(f"prep_bench --fabric: lanes=4 resolve_delay={resolve_delay}s")

    real_prepare = pl.AsyncBatchVerifier._prepare
    pl.AsyncBatchVerifier._prepare = staticmethod(
        slow_prepare(real_prepare, resolve_delay))
    os.environ["TM_TPU_FORCE_DEVICE"] = "1"
    v = pl.AsyncBatchVerifier(depth=2, pool_depth=OVERLAP_POOL_DEPTH)
    eng = fabric.IngressEngine()

    mtx = threading.Lock()
    results = {name: {} for name in ("mempool", "votes", "light")}

    def sink(name):
        def deliver(items, verdicts, err):
            with mtx:
                for i, it in enumerate(items):
                    results[name][it.item[3]] = (
                        None if err is not None else bool(verdicts[i]))
        return deliver

    def host_check(items):
        return [ed.verify_zip215_fast(t[0], t[1], t[2]) for t in items]

    common = dict(verifier=v, entries_fn=lambda t: t[:3],
                  host_fn=host_check)
    mp = eng.register(fabric.LaneSpec(
        name="mempool", priority=fabric.PRIORITY_INGRESS, batch=32,
        window_ms=4.0, use_completer=True, deliver=sink("mempool"),
        **common))
    vo = eng.register(fabric.LaneSpec(
        name="votes", priority=fabric.PRIORITY_CONSENSUS, batch=16,
        window_ms=4.0, adaptive=True, deliver=sink("votes"), **common))
    li = eng.register(fabric.LaneSpec(
        name="light", priority=fabric.PRIORITY_CONSENSUS, stepped=True,
        deliver=sink("light"), **common))
    rp = eng.register(fabric.LaneSpec(
        name="replay", priority=fabric.PRIORITY_REPLAY, stepped=True,
        **common))
    try:
        # -- one engine: four lanes, one scheduler, one completer --------
        names = [t.name for t in threading.enumerate()]
        n_sched = sum(n == "ingress-fabric-flush" for n in names)
        n_comp = sum(n == "ingress-fabric-complete" for n in names)
        print(f"  lanes registered           : {len(eng.lanes())} "
              f"(flush threads={n_sched}, completer threads={n_comp})")
        if len(eng.lanes()) != 4 or n_sched != 1 or n_comp != 1:
            print("  FAIL: expected 4 lanes on exactly one scheduler + "
                  "one completer thread", file=sys.stderr)
            rc = 1

        # -- mempool flood with one forged signature mid-flood -----------
        # (pre-sign everything: purepy signing is slow enough that
        # signing inside the submit loop would turn the flood into a
        # trickle and never fill a window)
        n_mp, forged_i = 96, 48
        mp_items = []
        for i in range(n_mp):
            pub, msg, sig, idx = signed("mempool", i)
            if i == forged_i:
                bad = bytearray(sig)
                bad[0] ^= 0x5A
                sig = bytes(bad)
            mp_items.append((pub, msg, sig, idx))
        n_vo = 128
        vo_items = [signed("votes", i) for i in range(n_vo)]
        trickle_items = [signed("votes", n_vo + i) for i in range(20)]

        for it in mp_items:
            mp.submit(it)
        mp.flush_now()

        # -- votes flood: the window must DEEPEN -------------------------
        # no flush_now() here: a manual flush would race the scheduler
        # and claim the whole flood under CAUSE_MANUAL (which by design
        # never adapts); the full-window force + timer tail drain it
        for it in vo_items:
            vo.submit(it)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            with mtx:
                if (len(results["mempool"]) >= n_mp
                        and len(results["votes"]) >= n_vo):
                    break
            time.sleep(0.01)
        grows = vo.ctrl.grows
        print(f"  votes flood                : {n_vo} sigs -> window "
              f"grows={grows} (target now {vo.ctrl.batch_target()}, "
              f"base 16)")
        if grows < 1:
            print("  FAIL: a flood at the batch target must deepen the "
                  "adaptive window", file=sys.stderr)
            rc = 1

        # -- votes idle trickle: the window must SHRINK back -------------
        trickles = 0
        for it in trickle_items:
            if vo.ctrl.shrinks >= 1:
                break
            vo.submit(it)
            trickles += 1
            time.sleep(0.12)
        shrinks = vo.ctrl.shrinks
        print(f"  votes idle trickle         : {trickles} lone sigs -> "
              f"window shrinks={shrinks} (target now "
              f"{vo.ctrl.batch_target()})")
        if shrinks < 1:
            print("  FAIL: an idle trickle must shrink the adaptive "
                  "window back toward its base", file=sys.stderr)
            rc = 1

        # -- stepped lanes: light host windows, replay block passthrough -
        n_li = 16
        for i in range(n_li):
            li.submit(signed("light", i))
        li.flush_pending()
        blk = EntryBlock.from_entries(
            [signed("replay", i)[:3] for i in range(16)])
        rp_verdicts = list(rp.submit_block(blk).result(timeout=60))

        # -- parity ------------------------------------------------------
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            with mtx:
                if len(results["votes"]) >= n_vo + trickles:
                    break
            time.sleep(0.01)
        with mtx:
            snapshot = {k: dict(d) for k, d in results.items()}
        snapshot["replay"] = {i: bool(x) for i, x in enumerate(rp_verdicts)}
        expect = {"mempool": n_mp, "votes": n_vo + trickles,
                  "light": n_li, "replay": 16}
        rejected = [(lane, i) for lane, d in snapshot.items()
                    for i, ok in d.items() if not ok]
        total = sum(len(d) for d in snapshot.values())
        print(f"  verdicts                   : {total} arrived, "
              f"rejected={rejected} (forged: mempool idx {forged_i})")
        for lane, n in expect.items():
            if len(snapshot[lane]) != n:
                print(f"  FAIL: {lane} delivered {len(snapshot[lane])}"
                      f"/{n} verdicts", file=sys.stderr)
                rc = 1
        if rejected != [("mempool", forged_i)]:
            print("  FAIL: the forged signature must be the ONLY "
                  "rejection", file=sys.stderr)
            rc = 1

        # -- pool hygiene ------------------------------------------------
        for lane in (mp, vo, li, rp):
            lane.close(timeout=30)
        drain_pool(v._pool)
        pool = v._pool.stats()
        print(f"  pool                       : {pool}")
        if pool["in_flight"] != 0:
            print(f"  FAIL: {pool['in_flight']} pool slots leaked",
                  file=sys.stderr)
            rc = 1
    finally:
        eng.close(timeout=5)
        v.close()
        os.environ.pop("TM_TPU_FORCE_DEVICE", None)
        pl.AsyncBatchVerifier._prepare = real_prepare
    return rc


def run_fleet(args) -> int:
    """--fleet: the round-18 verification-fleet gate on a mocked relay
    (slow readback over REAL kernels and REAL loopback sockets —
    verdicts are live, frames cross a real TCP stream). Asserts what
    the network-facing verify service must hold:

      coalesce  two client NODES submitting same-epoch blocks through
                ONE fleet server fuse into fewer device launches than
                the same blocks verified solo (sum of the two per-node
                launch counts) — the whole point of sharing the fleet
      blame     the one forged signature (node B, block 3, row 5) is
                the ONLY False verdict across both nodes, demuxed back
                to node B's future at the right row; verdict arrays are
                byte-identical to the solo runs
      failover  killing the fleet server mid-window loses ZERO items —
                every unresolved request fails over to the host path
                with identical verdicts — and a server restarted on the
                same port is rejoined automatically, after which the
                next submit rides the fleet again
      no leak   zero buffer-pool slots remain in flight once drained
    """
    import jax

    from tendermint_tpu.libs import jaxcache

    jaxcache.enable(jax, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from tendermint_tpu.crypto import ed25519 as ed
    from tendermint_tpu.fleet.client import FleetClient, FleetUnavailable
    from tendermint_tpu.fleet.server import FleetServer
    from tendermint_tpu.observability import trace as tr
    from tendermint_tpu.ops import pipeline as pl
    from tendermint_tpu.ops._testing import drain_pool, slow_prepare
    from tendermint_tpu.ops.entry_block import EntryBlock

    resolve_delay = 0.15
    n_keys, spb, bpn = 8, 16, 6  # sigs/block, blocks/node
    nodes = ("node-a", "node-b")
    forge_node, forge_block, forge_row = "node-b", 3, 5
    keys = [ed.gen_priv_key(bytes([i + 1]) * 32) for i in range(n_keys)]
    epoch = b"fleet-gate-epoch"  # unregistered: degrades to uncached prep

    print(f"prep_bench --fleet: nodes=2 blocks/node={bpn} sigs/block={spb} "
          f"resolve_delay={resolve_delay}s")
    rc = 0

    def build_block(node: str, b: int) -> EntryBlock:
        pub = np.zeros((spb, 32), dtype=np.uint8)
        sig = np.zeros((spb, 64), dtype=np.uint8)
        offsets = np.zeros(spb + 1, dtype=np.int64)
        msgs = []
        for i in range(spb):
            sk = keys[i % n_keys]
            m = f"fleet/{node}/{b}/{i}".encode()
            s = sk.sign(m)
            if (node, b, i) == (forge_node, forge_block, forge_row):
                bad = bytearray(s)
                bad[0] ^= 0x5A
                s = bytes(bad)
            pub[i] = np.frombuffer(sk.pub_key().bytes(), dtype=np.uint8)
            sig[i] = np.frombuffer(s, dtype=np.uint8)
            msgs.append(m)
            offsets[i + 1] = offsets[i] + len(m)
        return EntryBlock(
            pub, sig, b"".join(msgs), offsets,
            val_idx=np.arange(spb, dtype=np.int32), epoch_key=epoch)

    # pre-sign everything once (purepy signing is slow) and reuse the
    # SAME blocks across the solo and shared phases — parity by identity
    blocks = {node: [build_block(node, b) for b in range(bpn)]
              for node in nodes}

    def launches() -> int:
        return sum(1 for name, *_ in tr.TRACER.events()
                   if name == "pipeline.dispatch")

    real_prepare = pl.AsyncBatchVerifier._prepare
    pl.AsyncBatchVerifier._prepare = staticmethod(
        slow_prepare(real_prepare, resolve_delay))
    os.environ["TM_TPU_FORCE_DEVICE"] = "1"
    tr.TRACER.clear()
    tr.configure(enabled=True)
    try:
        # -- solo baselines: each node verifies its own blocks ------------
        # Arrivals are PACED (one block per `spacing`, like a live node's
        # request stream) in both phases: a solo node's trickle has no
        # coalescing partner, while the shared fleet sees both nodes'
        # streams and fuses across them — that cross-node fusion is the
        # whole economics of the fleet.
        spacing = 0.10
        solo_verdicts = {}
        solo_launches = {}
        for node in nodes:
            v = pl.AsyncBatchVerifier(depth=2, pool_depth=OVERLAP_POOL_DEPTH)
            try:
                before = launches()
                futs = []
                for i, blk in enumerate(blocks[node]):
                    futs.append(v.submit(blk, flow=1000 + i))
                    time.sleep(spacing)
                solo_verdicts[node] = [
                    np.asarray(f.result(timeout=300), dtype=bool)
                    for f in futs]
                solo_launches[node] = launches() - before
                drain_pool(v._pool)
            finally:
                v.close()
        solo_total = sum(solo_launches.values())
        print(f"  solo launches              : "
              f"{solo_launches['node-a']} + {solo_launches['node-b']} "
              f"= {solo_total} ({bpn} blocks each, one every "
              f"{spacing * 1e3:.0f} ms)")

        # -- shared fleet: both nodes through ONE server ------------------
        v = pl.AsyncBatchVerifier(depth=2, pool_depth=OVERLAP_POOL_DEPTH)
        srv = FleetServer(verifier=v).start()
        port = srv.addr[1]
        clients = {node: FleetClient(srv.addr, name=node, lane=node,
                                     timeout_ms=60_000, rejoin_ms=100)
                   for node in nodes}
        try:
            before = launches()
            futs = []
            for b in range(bpn):  # same per-node pacing as the solo phase
                for ni, node in enumerate(nodes):
                    futs.append((node, b, clients[node].submit(
                        blocks[node][b], flow=2000 + 100 * ni + b)))
                time.sleep(spacing)
            shared_verdicts = {node: [None] * bpn for node in nodes}
            for node, b, f in futs:
                shared_verdicts[node][b] = np.asarray(
                    f.result(timeout=300), dtype=bool)
            shared_launches = launches() - before
            print(f"  shared-fleet launches      : {shared_launches} "
                  f"({2 * bpn} blocks, 2 nodes, one server)")
            if shared_launches >= solo_total:
                print(f"  FAIL: {shared_launches} launches through the "
                      f"shared fleet vs {solo_total} solo — no cross-node "
                      f"coalescing", file=sys.stderr)
                rc = 1

            # -- verdict parity + blame demux ----------------------------
            mism = [
                (node, b)
                for node in nodes for b in range(bpn)
                if not np.array_equal(shared_verdicts[node][b],
                                      solo_verdicts[node][b])
            ]
            rejected = [
                (node, b, i)
                for node in nodes for b in range(bpn)
                for i in np.flatnonzero(~shared_verdicts[node][b])
            ]
            print(f"  verdict parity vs solo     : "
                  f"{'OK' if not mism else f'MISMATCH {mism}'}")
            print(f"  rejections                 : {rejected} "
                  f"(forged: {(forge_node, forge_block, forge_row)})")
            if mism:
                rc = 1
            if rejected != [(forge_node, forge_block, forge_row)]:
                print("  FAIL: the forged signature must be the ONLY "
                      "rejection, demuxed to the right node/row",
                      file=sys.stderr)
                rc = 1

            # -- failover: kill the server mid-window --------------------
            futs = [(node, b, clients[node].submit(blocks[node][b],
                                                   flow=3000 + b))
                    for b in range(bpn) for node in nodes]
            srv.stop()
            lost, fellback = 0, 0
            for node, b, f in futs:
                try:
                    got = np.asarray(f.result(timeout=120), dtype=bool)
                except FleetUnavailable:
                    # graceful degradation: host path, same verdicts
                    fellback += 1
                    blk = blocks[node][b]
                    got = np.asarray(
                        [ed.verify_zip215_fast(*blk.entry(i))
                         for i in range(len(blk))], dtype=bool)
                except Exception:  # noqa: BLE001 — any other loss counts
                    lost += 1
                    continue
                if not np.array_equal(got, solo_verdicts[node][b]):
                    lost += 1
            print(f"  fleet kill mid-window      : {len(futs)} in flight, "
                  f"{fellback} fell back to host, {lost} lost")
            if lost != 0 or fellback == 0:
                print("  FAIL: a fleet kill must lose ZERO items (host "
                      "fallback) and at least one request must have been "
                      "cut over", file=sys.stderr)
                rc = 1

            # -- rejoin: same port, fresh server -------------------------
            srv2 = FleetServer(addr=("127.0.0.1", port), verifier=v).start()
            try:
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    if all(c.connected for c in clients.values()):
                        break
                    time.sleep(0.02)
                rejoined = all(c.connected for c in clients.values())
                rejoins = {n: c.stats()["rejoins"]
                           for n, c in clients.items()}
                post = np.asarray(
                    clients["node-a"].submit(
                        blocks["node-a"][0], flow=4000).result(timeout=120),
                    dtype=bool)
                print(f"  rejoin after restart       : connected="
                      f"{rejoined} rejoins={rejoins}")
                if not rejoined or any(r < 1 for r in rejoins.values()):
                    print("  FAIL: clients must redial a restarted fleet "
                          "host automatically", file=sys.stderr)
                    rc = 1
                if not np.array_equal(post, solo_verdicts["node-a"][0]):
                    print("  FAIL: post-rejoin verdicts diverged",
                          file=sys.stderr)
                    rc = 1
            finally:
                srv2.stop()
        finally:
            for c in clients.values():
                c.close()
            srv.stop()
            drain_pool(v._pool)
            pool = v._pool.stats()
            v.close()

        # -- pool hygiene ------------------------------------------------
        print(f"  pool                       : {pool}")
        if pool["in_flight"] != 0:
            print(f"  FAIL: {pool['in_flight']} pool slots leaked",
                  file=sys.stderr)
            rc = 1
    finally:
        tr.configure(enabled=False)
        os.environ.pop("TM_TPU_FORCE_DEVICE", None)
        pl.AsyncBatchVerifier._prepare = real_prepare
    return rc


def run_soak(args) -> int:
    """--soak: the round-16 soak-harness gate on a mocked relay (verdicts
    come back all-accept with NO kernel — this gate checks the HARNESS,
    not the crypto). Asserts the three properties the soak driver must
    hold before its artifacts are trusted:

      cadence    the telemetry sampler ticks on SimClock cadence — two
                 same-seed mini-soaks produce the SAME tick count, and
                 that count matches duration/cadence (the sampler must
                 never free-run on wall time)
      replay     same-seed runs are replay-exact: identical cluster
                 fingerprint and network schedule digest (the soak loop
                 must not leak wall-clock reads into the trajectory)
      no leak    zero buffer-pool slots in flight once the shared
                 verifier drains, and tmlint's determinism rules stay at
                 0 findings with simnet/soak.py in scope
    """
    import math

    import jax

    from tendermint_tpu.libs import jaxcache

    jaxcache.enable(jax, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from tendermint_tpu.ops import pipeline as pl
    from tendermint_tpu.ops._testing import drain_pool, mock_mempool_prepare
    from tendermint_tpu.simnet.soak import SoakConfig
    from tendermint_tpu.simnet.soak import run_soak as _run_soak

    duration, cadence, rtt_ms = 6.0, 1.0, 2.0
    print(f"prep_bench --soak: duration={duration}vs cadence={cadence}s "
          f"runs=2 rtt={rtt_ms}ms relay=mocked")
    rc = 0

    real_prepare = pl.AsyncBatchVerifier._prepare
    pl.AsyncBatchVerifier._prepare = staticmethod(
        mock_mempool_prepare(real_prepare, rtt_ms / 1e3)
    )
    os.environ["TM_TPU_FORCE_DEVICE"] = "1"
    results, pools = [], []
    try:
        for _ in range(2):
            v = pl.AsyncBatchVerifier(depth=2)
            try:
                cfg = SoakConfig(duration_s=duration, seed=7,
                                 sample_every_s=cadence, max_wall_s=120.0)
                results.append(_run_soak(v, cfg))
                drain_pool(v._pool)
                pools.append(v._pool.stats())
            finally:
                v.close()
    finally:
        os.environ.pop("TM_TPU_FORCE_DEVICE", None)
        pl.AsyncBatchVerifier._prepare = real_prepare

    a, b = results

    # -- sampler cadence determinism -------------------------------------
    expect = math.floor(duration / cadence)
    print(f"  sampler ticks              : {a['sampler_ticks']} / "
          f"{b['sampler_ticks']} (expect ~{expect})")
    if a["sampler_ticks"] != b["sampler_ticks"]:
        print(f"  FAIL: tick count diverged across same-seed runs "
              f"({a['sampler_ticks']} vs {b['sampler_ticks']})",
              file=sys.stderr)
        rc = 1
    if abs(a["sampler_ticks"] - expect) > 1:
        print(f"  FAIL: {a['sampler_ticks']} ticks for {duration}s at "
              f"{cadence}s cadence (expect {expect}±1) — sampler is not "
              f"riding SimClock", file=sys.stderr)
        rc = 1

    # -- replay exactness ------------------------------------------------
    exact = (a["fingerprint"] == b["fingerprint"]
             and a["schedule_digest"] == b["schedule_digest"])
    print(f"  replay exact               : {exact} "
          f"(fp={a['fingerprint'][:16]}… heights={a['heights']})")
    if not exact:
        print("  FAIL: same-seed soak runs diverged — a wall-clock read "
              "leaked into the trajectory", file=sys.stderr)
        rc = 1
    for i, r in enumerate(results):
        if not r["ok"]:
            print(f"  FAIL: run {i} verdict not ok: {r.get('reason')}",
                  file=sys.stderr)
            rc = 1

    # -- pool hygiene ----------------------------------------------------
    for i, pool in enumerate(pools):
        print(f"  pool (run {i})               : {pool}")
        if pool["in_flight"] != 0:
            print(f"  FAIL: {pool['in_flight']} pool slots leaked",
                  file=sys.stderr)
            rc = 1

    # -- tmlint: soak.py is inside the determinism scope -----------------
    from tools.tmlint.__main__ import main as tmlint_main
    lint_rc = tmlint_main([])
    print(f"  tmlint tree gate           : rc={lint_rc} "
          f"(simnet/soak.py in scope)")
    if lint_rc != 0:
        print("  FAIL: tmlint found new findings with soak harness in "
              "scope", file=sys.stderr)
        rc = 1
    return rc


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sigs", type=int, default=10_000)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument(
        "--native",
        action="store_true",
        help="keep the native module (default: TM_TPU_NO_NATIVE=1 to bench "
        "the pure-Python fallback, the acceptance configuration)",
    )
    ap.add_argument(
        "--fused",
        action="store_true",
        help="round-6 gate: fused columnar-from-decode path vs the PR-2 "
        "columnar path (arg parity enforced, speedup gated)",
    )
    ap.add_argument(
        "--transfer",
        action="store_true",
        help="round-7 gate: warm-epoch H2D bytes <= 0.5x cold-epoch and "
        "cached per-signature prep >= 1.3x the PR-4 prep",
    )
    ap.add_argument(
        "--overlap",
        action="store_true",
        help="round-8 gate: dispatcher issues batch k+1's H2D transfer "
        "before blocking on kernel k (span-order proxy with a slow mock "
        "readback) and the buffer pool keeps steady-state allocations flat",
    )
    ap.add_argument(
        "--mesh",
        action="store_true",
        help="round-9 gate: mesh-dispatcher lane packing on a mocked "
        "2-lane mesh — pack/demux parity + blame, pure-pad-lane plan "
        "shape, zero slot leak, single relay owner, superbatch overlap",
    )
    ap.add_argument(
        "--light",
        action="store_true",
        help="round-11 gate: light-service batched verification on a "
        "mocked relay — cross-request same-epoch coalescing by launch "
        "count, verdict/blame parity vs the sequential verifier, memoized "
        "resubmission launches nothing, zero pool-slot leak",
    )
    ap.add_argument(
        "--ingress",
        action="store_true",
        help="round-13 gate: device-batched mempool CheckTx on a mocked "
        "relay — N flooded txs fuse into <= K launches, a mid-flood "
        "consensus batch preempts queued ingress work, a forged tx "
        "resolves FALSE (never dropped), zero pool-slot leak",
    )
    ap.add_argument(
        "--replay",
        action="store_true",
        help="round-14 gate: range-batched blocksync replay on a mocked "
        "relay — W same-epoch heights fuse into ceil(W*sigs/bucket) "
        "launches, a forged commit mid-range falls back per-height with "
        "verify_commit_light's exact error, zero pool-slot leak",
    )
    ap.add_argument(
        "--votes",
        action="store_true",
        help="round-15 gate: device-batched live-vote ingress on a mocked "
        "relay — N gossiped votes fuse into <= K launches, a forged "
        "signature mid-flood is the ONLY rejection, zero pool-slot leak",
    )
    ap.add_argument(
        "--fabric",
        action="store_true",
        help="round-17 gate: the unified ingress fabric on a mocked relay "
        "— four lane patterns on ONE scheduler + completer thread, the "
        "adaptive window deepens under flood AND shrinks back when idle, "
        "a forged signature is the only rejection, zero pool-slot leak",
    )
    ap.add_argument(
        "--fleet",
        action="store_true",
        help="round-18 gate: the network-facing verification fleet on a "
        "mocked relay over REAL loopback sockets — two client nodes' "
        "same-epoch blocks coalesce into fewer launches than solo, the "
        "one forged signature demuxes to the right node/row, a mid-window "
        "fleet kill loses zero items (host fallback) and a restarted "
        "server is rejoined, zero pool-slot leak",
    )
    ap.add_argument(
        "--schemes",
        action="store_true",
        help="round-19 gate: scheme-keyed verification lanes — a mixed "
        "ed25519+secp256k1 commit verifies in ONE superbatch launch with "
        "verdicts and blame byte-identical to the sequential walk, and "
        "the secp device lane matches the host per-signature loop "
        "bit-for-bit (incl. non-lower-S rejection)",
    )
    ap.add_argument(
        "--bls",
        action="store_true",
        help="round-20 gate: the BLS12-381 aggregation lane — K "
        "aggregated commits (one signature + signer bitmap each) verify "
        "in ONE fused multi-pairing launch with verdict codes and blame "
        "byte-identical to the pure-Python reference, incl. crafted "
        "non-subgroup G1/G2 points and the pre-crypto bitmap reject; an "
        "ed25519+secp256k1+bls three-lane superbatch is one launch",
    )
    ap.add_argument(
        "--soak",
        action="store_true",
        help="round-16 gate: soak-harness hygiene on a mocked relay — "
        "sampler ticks on SimClock cadence, same-seed runs replay-exact, "
        "zero pool-slot leak, tmlint clean with simnet/soak.py in scope",
    )
    args = ap.parse_args()
    if args.fused:
        return run_fused(args)
    if args.transfer:
        return run_transfer(args)
    if args.overlap:
        return run_overlap(args)
    if args.mesh:
        return run_mesh(args)
    if args.schemes:
        return run_schemes(args)
    if args.bls:
        return run_bls(args)
    if args.light:
        return run_light(args)
    if args.ingress:
        return run_ingress(args)
    if args.replay:
        return run_replay(args)
    if args.votes:
        return run_votes(args)
    if args.fabric:
        return run_fabric(args)
    if args.fleet:
        return run_fleet(args)
    if args.soak:
        return run_soak(args)

    from tendermint_tpu.native import load as _load_native
    from tendermint_tpu.ops import backend, pipeline
    from tendermint_tpu.ops.entry_block import EntryBlock

    chain_id = "prep-bench"
    vset, commit = build_synthetic_commit(args.sigs)
    needed = vset.total_voting_power() * 2 // 3
    bucket = backend._bucket_for(args.sigs)
    native = _load_native()
    print(
        f"prep_bench: n={args.sigs} bucket={bucket} reps={args.reps} "
        f"native={'yes' if native is not None else 'no'} "
        f"backend={os.environ.get('JAX_PLATFORMS', '?')}"
    )

    def run(fn):
        times = []
        for _ in range(args.reps):
            # fresh sign-bytes template cache per rep: both paths pay the
            # one-time template build identically
            commit._sb_tpl = None
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        return statistics.median(times)

    # The pipeline's prep selection on this (CPU/XLA) config: canonical
    # vote sign-bytes fit DEVICE_HASH_MAX_MSG, so the worker preps via
    # prepare_batch_device_hash — no host SHA-512 (pipeline._prepare).
    # That is the PRIMARY measured path and the acceptance gate; the
    # host-hash prep (what the TPU pallas/RLC paths pay for challenges)
    # is reported as a secondary figure.
    results = {}
    for name, prep in (
        ("pipeline prep (device-hash)", backend.prepare_batch_device_hash),
        ("host-hash prep", backend.prepare_batch),
    ):
        t_tuple = run(
            lambda p=prep: p(
                commit_entries_tuples(chain_id, vset, commit, needed), bucket
            )
        )
        t_block = run(
            lambda p=prep: p(
                pipeline.commit_entries(chain_id, vset, commit, needed)[0],
                bucket,
            )
        )
        # parity spot-check while we're here: identical kernel args
        commit._sb_tpl = None
        a_t = prep(commit_entries_tuples(chain_id, vset, commit, needed), bucket)
        commit._sb_tpl = None
        a_b = prep(
            pipeline.commit_entries(chain_id, vset, commit, needed)[0], bucket
        )
        parity = all(np.array_equal(x, y) for x, y in zip(a_t, a_b))
        speedup = t_tuple / t_block if t_block else float("inf")
        results[name] = (t_tuple, t_block, speedup, parity)
        print(f"  {name}:")
        print(f"    tuple-list baseline : {t_tuple * 1e3:9.2f} ms")
        print(f"    EntryBlock columnar : {t_block * 1e3:9.2f} ms")
        print(f"    speedup             : {speedup:9.2f}x")
        print(f"    arg parity          : {'OK' if parity else 'MISMATCH'}")

    if not all(r[3] for r in results.values()):
        return 2
    # acceptance gate (ISSUE 2): >= 2x on the pure-Python fallback for
    # the path the pipeline actually selects under JAX_PLATFORMS=cpu
    gate = results["pipeline prep (device-hash)"][2]
    if native is None and gate < 2.0:
        print("  FAIL: expected >= 2x host prep reduction", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
