# tools/ is a package so `python -m tools.tmlint` works from the repo root.
