#!/usr/bin/env python3
"""soak_report — render a SOAK_r*.json artifact (ISSUE 16).

Text-mode rendering of the soak harness's time-series telemetry:

- run verdict + per-lane SLO table (observed vs budget),
- a sparkline trajectory per sampled gauge series (min/max/last),
- per-lane latency p99 trajectory over the SLO windows,
- breach localization: the worst time window per breached budget and
  the dominating span category inside it (when the artifact has span
  attribution).

Usage:
    python tools/soak_report.py SOAK_r01.json
    python tools/soak_report.py --width 48 path/to/artifact.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

BLOCKS = "▁▂▃▄▅▆▇█"  # ▁▂▃▄▅▆▇█


def downsample(vals, width: int):
    """Bucket-mean a series down to at most `width` points."""
    vals = list(vals)
    if len(vals) <= width:
        return vals
    out = []
    n = len(vals)
    for i in range(width):
        lo = i * n // width
        hi = max((i + 1) * n // width, lo + 1)
        grp = vals[lo:hi]
        out.append(sum(grp) / len(grp))
    return out


def spark(vals, width: int = 64) -> str:
    """Sparkline-style text trajectory (scaled to the series' own
    min..max; a flat series renders as a flat low line)."""
    vals = downsample([float(v) for v in vals], width)
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return BLOCKS[0] * len(vals)
    span = hi - lo
    return "".join(
        BLOCKS[min(int((v - lo) / span * len(BLOCKS)), len(BLOCKS) - 1)]
        for v in vals
    )


def _fmt(v, nd=1):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render(doc: dict, width: int = 64) -> str:
    t0 = float(doc.get("t_start_virtual_s") or 0.0)
    lines = []
    ok = doc.get("ok")
    lines.append(
        f"soak verdict: {'OK' if ok else 'FAIL'}"
        + (f" — {doc['reason']}" if doc.get("reason") else "")
    )
    lines.append(
        f"  seed={doc.get('seed')} nodes={doc.get('n_nodes')} "
        f"virtual={_fmt(doc.get('virtual_s'))}s "
        f"wall={_fmt(doc.get('wall_s'))}s "
        f"heights={doc.get('heights')} "
        f"mode={doc.get('mode', '?')}"
    )
    cu = (doc.get("catchup") or [None])[0]
    if cu:
        lines.append(
            f"  catchup: node {cu.get('node')} behind_at_start="
            f"{cu.get('behind_at_start')} applied={cu.get('heights_applied')}"
            f" hit_rate={_fmt(cu.get('hit_rate'), 3)} rejoined="
            f"{cu.get('rejoined')} "
            f"replay={_fmt(doc.get('replay_heights_per_s'))} heights/s"
        )
    lines.append("")

    # -- SLO table ---------------------------------------------------------
    slo = doc.get("slo") or {}
    lines.append(f"SLO budgets ({len(slo.get('results', []))} evaluated, "
                 f"{len(slo.get('breaches', []))} breached):")
    for r in slo.get("results", []):
        mark = "ok  " if r.get("ok") else "FAIL"
        cmp_ = "<=" if r.get("kind") == "p99_ms_max" else ">="
        lines.append(
            f"  [{mark}] {r.get('slo'):<28} lane={r.get('lane'):<10} "
            f"observed={_fmt(r.get('observed'), 2):>10} {cmp_} "
            f"limit={_fmt(r.get('limit'), 2)}"
            + (f"  ({r['reason']})" if r.get("reason") else "")
        )
    lines.append("")

    # -- per-lane latency trajectory over windows --------------------------
    windows = doc.get("windows") or {}
    if windows:
        lines.append("lane latency p99 trajectory (per SLO window):")
        for lane in sorted(windows):
            wins = windows[lane]
            if not wins:
                continue
            p99s = [w["p99_ms"] for w in wins]
            lines.append(
                f"  {lane:<16} {spark(p99s, width)}  "
                f"p99 {_fmt(min(p99s))}..{_fmt(max(p99s))} ms "
                f"({sum(w['count'] for w in wins)} samples)"
            )
        lines.append("")

    # -- breach localization ----------------------------------------------
    breaches = slo.get("breaches") or []
    if breaches:
        lines.append("breach localization:")
        for b in breaches:
            bw = b.get("breach_window")
            if not bw:
                lines.append(
                    f"  {b.get('slo')}: no samples to localize"
                    + (f" — {b['reason']}" if b.get("reason") else "")
                )
                continue
            w0 = bw["t0"] - t0
            w1 = bw["t1"] - t0
            lines.append(
                f"  {b.get('slo')} (lane {b.get('lane')}): worst window "
                f"t+{w0:.1f}s..t+{w1:.1f}s — p99 {_fmt(bw.get('p99_ms'), 1)} "
                f"ms over {bw.get('count')} samples"
            )
            dom = bw.get("dominant_span")
            if dom:
                lines.append(f"    dominating span category: {dom}")
                totals = bw.get("span_totals_ms") or {}
                for name, ms in sorted(
                    totals.items(), key=lambda kv: -kv[1]
                )[:5]:
                    lines.append(f"      {name:<28} {_fmt(ms, 1):>10} ms")
        lines.append("")

    # -- gauge trajectories ------------------------------------------------
    gauges = doc.get("gauges") or {}
    if gauges:
        lines.append(f"gauge time series ({doc.get('sampler_ticks')} ticks):")
        for name in sorted(gauges):
            pts = gauges[name]
            if not pts:
                continue
            vals = [p[1] for p in pts]
            lines.append(
                f"  {name:<44} {spark(vals, width)}  "
                f"[{_fmt(min(vals))}..{_fmt(max(vals))}] last={_fmt(vals[-1])}"
            )
        lines.append("")

    counters = doc.get("counters") or {}
    if counters:
        lines.append("lane counters: " + ", ".join(
            f"{k}={v}" for k, v in counters.items()))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("artifact", nargs="?", default="SOAK_r01.json",
                    help="soak artifact path (default SOAK_r01.json)")
    ap.add_argument("--width", type=int, default=64,
                    help="sparkline width in characters (default 64)")
    args = ap.parse_args(argv)
    if not os.path.exists(args.artifact):
        print(f"error: no artifact at {args.artifact}", file=sys.stderr)
        return 2
    with open(args.artifact) as fh:
        doc = json.load(fh)
    print(render(doc, width=max(args.width, 8)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
